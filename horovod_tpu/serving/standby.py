"""Warm-standby serving frontend: replication client + promotion logic.

The serving plane's analog of :class:`~..runtime.standby.StandbyCoordinator`
(docs/inference.md failure matrix, "frontend dies" row). A second frontend
process runs a :class:`ServingStandby`: it dials the active frontend's
listener with ``MSG_REPL_HELLO`` payload ``b"serve"``, receives one
``MSG_SNAPSHOT`` of the durable request state — the finished-result LRU
(dedupe answers) plus every open submit payload — and then applies a
``MSG_JOURNAL`` record per accepted submit, terminal result, and cancel.

The replicated state is exactly what exactly-once needs and nothing more:

* **results** — so duplicate submits replayed by reconnecting clients are
  answered from cache, never re-generated, across the failover boundary.
* **pending submits** — so requests the old frontend accepted but had not
  answered re-enter the dispatch queue on the promotee; the blind replay
  clients do on reconnect makes delivery certain even for requests that
  raced the crash (they dedupe against the seeded pending map).

Dispatch assignments and worker inflight counts are NOT replicated: the
promoted frontend starts with an empty worker table and simply re-dispatches
everything pending as workers re-HELLO — worker-side ``_seen`` dedupe and
the result LRU make the re-send idempotent.

Promotion mirrors the coordinator rules exactly:

* **Lease mode** (``HOROVOD_LEASE_TTL`` + ``HVD_KV_ADDR``): stream loss
  alone never promotes. The standby watches ``serve.lease.{gen}`` and
  takes over only after a full TTL of observed stasis on its own clock,
  by winning the CAS (epoch+1). The new epoch fences the old frontend's
  frames everywhere.
* **Crash-only mode** (no lease): a few quick re-dials, then promote.
  Fencing is toothless (epoch stays 0) — same documented trade-off as the
  coordinator plane.

The promoted frontend publishes ``serve.addr.{gen}.f1``; workers and
clients probe that key after their reconnect backoff fails against the
dead address. One failover deep by design.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import blackbox as _blackbox
from ..exceptions import ShutdownError
from ..metrics import instruments
from ..runtime import lease as _lease_mod
from ..runtime import wire
from ..runtime.coordinator import (MSG_BYE, MSG_JOURNAL, MSG_SNAPSHOT,
                                   _advertise_host, _publish_key)
from ..runtime.standby import dial_repl
from .server import ServingFrontend

logger = logging.getLogger("horovod_tpu")


class ServingStandby:
    """A warm frontend replica: mirrors the primary's request ledger and
    promotes itself into a live :class:`ServingFrontend` when the primary
    dies (lease-gated when fencing is configured)."""

    def __init__(self, primary_addr: Tuple[str, int], secret: str,
                 rank: int = 1, gen: int = 0):
        self._addr = primary_addr
        self._secret = secret
        self._rank = rank
        self._gen = gen
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._have_snapshot = False
        # replica of the primary's durable request state
        self._results: "Dict[str, bytes]" = {}   # rid -> RESULT payload
        self._pending: "Dict[str, bytes]" = {}   # rid -> SUBMIT payload
        self._epoch = 0
        self.promoted = False
        self.frontend: Optional[ServingFrontend] = None
        self._guard = wire.FenceGuard(rank=rank)
        self._lease = (_lease_mod.LeaseManager(
            gen, rank, key=f"serve.lease.{gen}")
            if _lease_mod.lease_enabled() else None)
        self._lease_watching = False
        self._thread = threading.Thread(
            target=self._run, name="hvd-serve-standby", daemon=True)

    def start(self) -> "ServingStandby":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._lease is not None:
            self._lease.stop()
        with self._lock:
            fe = self.frontend
        if fe is not None:
            fe.stop()

    def wait_promoted(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.promoted:
                return True
            if self._stop.wait(0.05):
                return False
        return False

    # ------------------------------------------------------- replication
    def _dial(self) -> socket.socket:
        return dial_repl(self._addr, self._secret, self._rank,
                         hello_payload=b"serve", fence=self._guard.epoch)

    def _run(self) -> None:
        sock: Optional[socket.socket] = None
        for _ in range(5):
            try:
                sock = self._dial()
                break
            except (ConnectionError, OSError):
                if self._stop.wait(0.2):
                    return
        if sock is None:
            logger.warning("serving standby: never reached the primary's "
                           "replication endpoint; standby inactive")
            return
        try:
            while not self._stop.is_set():
                try:
                    mt, _, _, payload = wire.recv_frame(
                        sock, self._secret, self._stop, guard=self._guard)
                except ShutdownError:
                    return
                except wire.FenceError as exc:
                    # the deposed primary confirming it fenced — done
                    logger.info("serving standby: deposed primary's frame "
                                "rejected (%s)", exc)
                    return
                except (ConnectionError, OSError) as exc:
                    if self._stop.is_set():
                        return
                    if self._lease is not None:
                        # lease mode: the watcher alone promotes; keep a
                        # path open for a healed primary's BYE/frames
                        redialed = self._redial(120, 0.5)
                        if redialed is None:
                            return
                        sock = redialed
                        continue
                    redialed = self._redial(3, 0.3)
                    if redialed is not None:
                        sock = redialed
                        continue
                    if self._have_snapshot:
                        self._promote(exc)
                    return
                if mt == MSG_SNAPSHOT:
                    epoch, results, pending = wire.decode_serve_snapshot(
                        payload)
                    with self._lock:
                        self._epoch = epoch
                        self._results = {
                            wire.decode_serve_result(b)[0]: b
                            for b in results}
                        self._pending = {
                            wire.decode_serve_submit_ex(b)[0]: b
                            for b in pending}
                    self._have_snapshot = True
                    logger.info(
                        "serving standby: snapshot applied (%d results, "
                        "%d pending, epoch %d)", len(results),
                        len(pending), epoch)
                    if self._lease is not None and not self._lease_watching:
                        self._lease_watching = True
                        threading.Thread(target=self._lease_watch,
                                         name="hvd-serve-lease-watch",
                                         daemon=True).start()
                elif mt == MSG_JOURNAL:
                    self._apply_journal(payload)
                elif mt == MSG_BYE:
                    logger.info("serving standby: primary said BYE; "
                                "standing down")
                    return
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _apply_journal(self, payload: bytes) -> None:
        kind, blob = wire.decode_serve_journal(payload)
        with self._lock:
            if kind == wire.SERVE_J_SUBMIT:
                rid = wire.decode_serve_submit_ex(blob)[0]
                if rid not in self._results:
                    self._pending[rid] = blob
            elif kind == wire.SERVE_J_RESULT:
                rid = wire.decode_serve_result(blob)[0]
                self._results[rid] = blob
                self._pending.pop(rid, None)
            elif kind == wire.SERVE_J_CANCEL:
                rid, reason = wire.decode_serve_cancel(blob)
                self._pending.pop(rid, None)
                # tombstone: a replayed duplicate must see CANCELLED, not
                # trigger a fresh generation on the promotee
                self._results[rid] = wire.encode_serve_result(
                    rid, wire.SERVE_CANCELLED, [], reason)

    def _redial(self, attempts: int, pause: float
                ) -> Optional[socket.socket]:
        for _ in range(attempts):
            if self._stop.wait(pause):
                return None
            try:
                return self._dial()
            except (ConnectionError, OSError):
                continue
        return None

    # ------------------------------------------------------ lease watcher
    def _lease_watch(self) -> None:
        """Observed-stasis takeover on ``serve.lease.{gen}`` — identical
        protocol to the coordinator standby's watcher: a full TTL of
        stasis on our own clock, then the CAS decides."""
        assert self._lease is not None
        poll = min(self._lease.renew_interval, 0.25)
        ttl = self._lease.ttl
        last_val: Optional[bytes] = None
        last_change = time.monotonic()
        while not self._stop.wait(poll):
            if self.promoted:
                return
            try:
                val = self._lease.read()
            except (ConnectionError, OSError):
                last_change = time.monotonic()  # blind ≠ stasis
                continue
            if val != last_val:
                last_val = val
                last_change = time.monotonic()
                continue
            if time.monotonic() - last_change < ttl:
                continue
            if not self._have_snapshot:
                continue
            try:
                epoch = self._lease.acquire_over(val)
            except (ConnectionError, OSError):
                last_change = time.monotonic()
                continue
            if epoch is None:
                last_val = None  # lost the race; observe afresh
                last_change = time.monotonic()
                continue
            self._guard.observe(epoch)
            self._promote(
                RuntimeError("serving lease expired: full TTL of observed "
                             "stasis"), fence_epoch=epoch)
            return

    # --------------------------------------------------------- promotion
    def _promote(self, why: Exception, fence_epoch: int = 0) -> None:
        with self._lock:
            if self.promoted:
                return
            results = list(self._results.values())
            pending = list(self._pending.values())
        advertise = _advertise_host()
        bind = "127.0.0.1" if advertise == "127.0.0.1" else "0.0.0.0"
        fe = ServingFrontend(host=bind, port=0, secret=self._secret,
                             rank=self._rank, gen=self._gen,
                             fence_epoch=fence_epoch)
        # seed the ledger BEFORE opening for traffic: the first replayed
        # submit must already hit the dedupe cache / pending map
        fe.seed_state(results, pending)
        if self._lease is not None and fence_epoch:
            # the promotee now holds the lease; losing it later fences it
            # by the same rule the old primary obeyed
            fe.attach_lease(self._lease)
        fe.start()
        with self._lock:
            self.frontend = fe
            self.promoted = True
        try:
            _publish_key(f"serve.addr.{self._gen}.f1",
                         f"{advertise}:{fe.addr[1]}", self._secret)
        except (ConnectionError, OSError, KeyError, RuntimeError) as exc:
            # no rendezvous KV (e.g. a direct-addressed pod): peers find
            # the promotee by probing their configured address list
            logger.warning("serving standby: failover address publish "
                           "failed: %s", exc)
        instruments.serving_frontend_failovers().inc()
        _blackbox.record(
            _blackbox.K_FAILOVER, "rank_%d" % self._rank,
            "serving standby promoted to frontend at %s:%d "
            "(epoch %d, %d results, %d pending re-queued) after %s"
            % (advertise, fe.addr[1], fence_epoch, len(results),
               len(pending), why),
            rank=self._rank)
        logger.warning(
            "serving standby: PROMOTED to frontend at %s:%d (epoch %d, "
            "%d pending re-queued): %s", advertise, fe.addr[1],
            fence_epoch, len(pending), why)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m horovod_tpu.serving.standby`` — the warm-standby
    process the chaos drills pair with a SIGKILLed primary."""
    import argparse

    ap = argparse.ArgumentParser(
        description="horovod_tpu serving frontend warm standby")
    ap.add_argument("--primary", required=True, metavar="HOST:PORT")
    ap.add_argument("--rank", type=int, default=1)
    ap.add_argument("--gen", type=int, default=0)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s standby %(message)s")
    import os

    _blackbox.maybe_activate()
    _blackbox.set_identity(args.rank, 2)
    host, port = args.primary.rsplit(":", 1)
    sb = ServingStandby((host, int(port)),
                        os.environ.get("HVD_SECRET", ""),
                        rank=args.rank, gen=args.gen)
    sb.start()
    try:
        while True:
            time.sleep(0.5)
            _blackbox.dump("serving standby periodic flush", force=True)
    except KeyboardInterrupt:
        sb.stop()
        _blackbox.dump("serving standby exit", force=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
