"""Paged KV cache for inference serving (docs/inference.md).

The cache is a fixed pool of ``num_blocks`` blocks of ``block_size`` token
slots each, per transformer layer — the vLLM paged-attention idea scaled to
this repo's correctness-first CPU/TPU-host serving loop: requests own
*block tables* (lists of pool block indices), tokens append into the last
block until it fills, and freeing a request returns whole blocks to the
free list. Fragmentation is therefore bounded at one partial block per
request, and admission control can reason in whole blocks.

Compute-side, :meth:`PagedKVCache.gather` flattens each request's blocks
into one padded ``[num_layers, B, capacity, H, Dh]`` window plus a slot
validity mask — the shape-stable operand ``models/transformer.py``'s
``cached_attention`` masks exactly (padding contributes exactly 0.0), which
is what makes batched decode bit-identical to sequential decode.

Occupancy accounting is two-level, matching the ``hvd_serving_kv_*``
gauges: *blocks* (allocated out of the pool — the admission currency) and
*tokens* (slots actually written — the live-context payload).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


class KVCacheFull(RuntimeError):
    """The block pool cannot satisfy an allocation (admission control
    should have prevented this — seeing it means a reservation bug)."""


def blocks_for_tokens(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` slots (ceil division, min 1 so a
    zero-token reservation still owns an append target)."""
    return max(1, -(-int(tokens) // int(block_size)))


class BlockAllocator:
    """Free-list allocator over a fixed pool of block ids."""

    def __init__(self, num_blocks: int):
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise KVCacheFull(
                f"requested {n} KV blocks with {len(self._free)} free "
                f"(pool {self.num_blocks})")
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(f"freeing unknown block id {b}")
        self._free.extend(blocks)
        if len(self._free) > self.num_blocks:
            raise ValueError("double free: free list exceeds pool size")


class PagedKVCache:
    """Block-pooled per-layer K/V storage with per-request block tables.

    ``shape``: (num_layers, num_heads, head_dim). The pool arrays live on
    the host (numpy): the serving loop writes decode-step K/V back from
    device and gathers padded windows per step — the layout a future
    device-resident paged-attention kernel would consume directly.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 num_layers: int, num_heads: int, head_dim: int,
                 dtype=np.float32):
        self.block_size = int(block_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.allocator = BlockAllocator(num_blocks)
        shape = (self.num_layers, self.allocator.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k_pool = np.zeros(shape, dtype)
        self.v_pool = np.zeros(shape, dtype)
        # request id -> (block table, tokens written)
        self._tables: Dict[str, Tuple[List[int], int]] = {}

    # ---------------------------------------------------------- accounting
    @property
    def num_blocks(self) -> int:
        return self.allocator.num_blocks

    @property
    def used_blocks(self) -> int:
        return self.allocator.used_blocks

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    @property
    def used_tokens(self) -> int:
        return sum(used for _, used in self._tables.values())

    def occupancy(self) -> float:
        """Fraction of the pool's blocks allocated (the admission-facing
        number the ``hvd_serving_kv_occupancy`` gauge exports)."""
        return self.allocator.used_blocks / max(1, self.num_blocks)

    def length(self, request_id: str) -> int:
        return self._tables[request_id][1]

    def block_table(self, request_id: str) -> List[int]:
        return list(self._tables[request_id][0])

    def requests(self) -> List[str]:
        return sorted(self._tables)

    # ---------------------------------------------------------- lifecycle
    def allocate(self, request_id: str, max_tokens: int) -> int:
        """Reserve the whole block budget for a request up front
        (prompt + max generated tokens). Upfront reservation is the
        admission-control contract: an admitted request can NEVER stall
        mid-decode on a full pool. Returns the block count."""
        if request_id in self._tables:
            raise ValueError(f"request {request_id!r} already allocated")
        n = blocks_for_tokens(max_tokens, self.block_size)
        blocks = self.allocator.allocate(n)
        self._tables[request_id] = (blocks, 0)
        return n

    def free(self, request_id: str) -> int:
        """Release a finished request's blocks; returns the count."""
        blocks, _ = self._tables.pop(request_id)
        self.allocator.free(blocks)
        return len(blocks)

    # ------------------------------------------------------------- writes
    def append(self, request_id: str, k: np.ndarray, v: np.ndarray) -> None:
        """Write new-token K/V for one request. ``k``/``v``:
        [num_layers, T, H, Dh] (T tokens, typically the prompt at prefill
        and 1 at decode)."""
        blocks, used = self._tables[request_id]
        t = k.shape[1]
        if used + t > len(blocks) * self.block_size:
            raise KVCacheFull(
                f"request {request_id!r}: {used}+{t} tokens exceeds its "
                f"{len(blocks)}-block reservation")
        for i in range(t):
            slot = used + i
            blk = blocks[slot // self.block_size]
            off = slot % self.block_size
            self.k_pool[:, blk, off] = k[:, i]
            self.v_pool[:, blk, off] = v[:, i]
        self._tables[request_id] = (blocks, used + t)

    # ------------------------------------------------------------- reads
    def gather(self, request_ids: List[str], capacity: int):
        """Padded decode operand for a batch of requests.

        Returns ``(k, v, mask, lengths)``: ``k``/``v``
        [num_layers, B, capacity, H, Dh], ``mask`` bool [B, capacity]
        (True = slot holds a real token), ``lengths`` int32 [B]. Request
        ids absent from the cache (batch-padding slots) yield all-False
        rows. ``capacity`` is FIXED by the engine so every decode step
        compiles to one program and stays shape-stable (the bit-parity
        precondition)."""
        b = len(request_ids)
        shape = (self.num_layers, b, int(capacity), self.num_heads,
                 self.head_dim)
        k = np.zeros(shape, self.k_pool.dtype)
        v = np.zeros(shape, self.v_pool.dtype)
        mask = np.zeros((b, int(capacity)), bool)
        lengths = np.zeros((b,), np.int32)
        for row, rid in enumerate(request_ids):
            entry = self._tables.get(rid)
            if entry is None:
                continue
            blocks, used = entry
            if used > capacity:
                raise ValueError(
                    f"request {rid!r} holds {used} tokens > gather "
                    f"capacity {capacity}")
            if used:
                nb = blocks_for_tokens(used, self.block_size)
                flat = self.k_pool[:, blocks[:nb]].reshape(
                    self.num_layers, nb * self.block_size,
                    self.num_heads, self.head_dim)
                k[:, row, :used] = flat[:, :used]
                flat = self.v_pool[:, blocks[:nb]].reshape(
                    self.num_layers, nb * self.block_size,
                    self.num_heads, self.head_dim)
                v[:, row, :used] = flat[:, :used]
            mask[row, :used] = True
            lengths[row] = used
        return k, v, mask, lengths
