"""Inference serving subsystem (docs/inference.md).

Turns the training stack into a server: a continuous-batching scheduler
admits requests against a paged KV cache, a :class:`ServingEngine` runs
the prefill/decode loop over the sharded ``models/transformer.py``
TransformerLM, and the ``server``/``worker``/``client`` modules put the
whole thing behind the PR-4 hardened control plane (framed TCP with
CRC/HMAC, heartbeats, liveness, elastic worker re-admission).

Quick start (in-process, single replica)::

    from horovod_tpu.serving import ServingConfig, ServingEngine
    engine = ServingEngine(model, params,
                           ServingConfig(num_blocks=64)).start()
    req = engine.submit(prompt_tokens, max_new_tokens=32)
    print(req.result(timeout=60))

For the networked pod-serving mode (frontend + N worker replicas +
clients) see ``serving/server.py`` and ``examples/serve_transformer_lm.py``.
"""

from .client import ClientRequest, ServingClient
from .engine import ServingConfig, ServingEngine
from .kvcache import BlockAllocator, KVCacheFull, PagedKVCache, \
    blocks_for_tokens
from .scheduler import (ACTIVE, CANCELLED, DONE, FAILED, QUEUED,
                        ContinuousBatchingScheduler, QueueFull, Request)
from .server import ServingFrontend
from .standby import ServingStandby
from .worker import ServingWorker, build_replica_engine

__all__ = [
    "ServingConfig", "ServingEngine",
    "PagedKVCache", "BlockAllocator", "KVCacheFull", "blocks_for_tokens",
    "ContinuousBatchingScheduler", "Request", "QueueFull",
    "QUEUED", "ACTIVE", "DONE", "FAILED", "CANCELLED",
    "ServingFrontend", "ServingStandby", "ServingWorker",
    "build_replica_engine", "ServingClient", "ClientRequest",
]
