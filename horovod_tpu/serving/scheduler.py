"""Continuous-batching request scheduler (docs/inference.md).

The scheduling model is the orca/vLLM-style iteration-level loop: every
engine step admits at most ``prefill_per_step`` queued requests (each
prefill is a full-prompt forward) and then decodes ONE token for EVERY
in-flight request as a single batched forward — new requests join the
decode batch at the next step instead of waiting for a full batch to
drain, and short requests leave without stalling long ones.

Admission control is reservation-based: a request is admitted only when
its whole KV budget (prompt + max_new_tokens, rounded up to blocks) fits
the free pool AND a decode-batch slot is free. Admitted work therefore
never deadlocks on cache space mid-flight; everything else waits in a
bounded FCFS queue, and a full queue rejects at submit time (the
backpressure signal the serving frontend turns into a retryable
``SERVE_REJECTED``).

Fairness is FCFS at admission plus every-request-every-step at decode:
there is no priority lane, so the only reordering possible is a large
request waiting for blocks while smaller later arrivals fit — bounded by
``strict_fifo`` (default True: the queue head blocks admission until it
fits, trading utilization for no-starvation).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Callable, List, Optional, Tuple

from .kvcache import PagedKVCache, blocks_for_tokens

# request lifecycle states
QUEUED = "queued"
ACTIVE = "active"      # prefilled; in the decode batch
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"  # terminal: deadline / client abandon / TTL sweep


class QueueFull(RuntimeError):
    """Admission control rejected the submit: the bounded request queue is
    at capacity. Retryable — back off and resubmit."""


class Request:
    """One in-flight generation request."""

    _ids = itertools.count()

    __slots__ = ("id", "prompt", "max_new_tokens", "eos_id", "state",
                 "output", "error", "submitted_t", "admitted_t",
                 "first_token_t", "done_t", "callback", "deadline_t",
                 "_done_event")

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 eos_id: Optional[int] = None, request_id: Optional[str]
                 = None, callback: Optional[Callable] = None,
                 deadline: Optional[float] = None):
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.id = (request_id if request_id is not None
                   else "req-%d" % next(Request._ids))
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.state = QUEUED
        self.output: List[int] = []      # generated tokens (prompt excluded)
        self.error = ""
        self.submitted_t = time.monotonic()
        self.admitted_t = None
        self.first_token_t = None
        self.done_t = None
        self.callback = callback
        # absolute monotonic cutoff; the deadline arrives as a RELATIVE
        # budget on the wire and is re-anchored here on this host's clock
        self.deadline_t = (self.submitted_t + float(deadline)
                           if deadline else None)
        self._done_event = threading.Event()

    # ------------------------------------------------------------- result
    def done(self) -> bool:
        return self._done_event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done_event.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done_event.wait(timeout):
            raise TimeoutError(f"request {self.id} not done")
        if self.state == FAILED:
            raise RuntimeError(f"request {self.id} failed: {self.error}")
        return list(self.output)

    def latency(self) -> Optional[float]:
        if self.done_t is None:
            return None
        return self.done_t - self.submitted_t

    def finish(self, state: str, error: str = "") -> None:
        self.state = state
        self.error = error
        self.done_t = time.monotonic()
        self._done_event.set()
        if self.callback is not None:
            self.callback(self)

    def total_tokens(self) -> int:
        return len(self.prompt) + self.max_new_tokens


class ContinuousBatchingScheduler:
    """Admission + iteration-level batching over a :class:`PagedKVCache`.

    Thread-safe: the serving frontend submits from connection threads
    while the engine thread runs :meth:`schedule` / completion paths.
    """

    def __init__(self, cache: PagedKVCache, max_batch: int = 8,
                 max_queue: int = 128, max_context: Optional[int] = None,
                 prefill_per_step: int = 1, strict_fifo: bool = True,
                 request_ttl: Optional[float] = None):
        self.cache = cache
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.max_context = (int(max_context) if max_context is not None
                            else cache.num_blocks * cache.block_size)
        self.prefill_per_step = max(1, int(prefill_per_step))
        self.strict_fifo = bool(strict_fifo)
        # max lifetime for ANY request (HOROVOD_SERVING_REQUEST_TTL): the
        # backstop against orphans whose client vanished without a cancel —
        # without it an abandoned request holds its KV reservation forever
        if request_ttl is None:
            request_ttl = float(
                os.environ.get("HOROVOD_SERVING_REQUEST_TTL") or 0.0)
        self.request_ttl = request_ttl if request_ttl > 0 else None
        self.lock = threading.RLock()
        self.waiting: List[Request] = []
        self.active: List[Request] = []
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.cancelled = 0
        self.expired = 0

    # ---------------------------------------------------------- admission
    def submit(self, request: Request) -> Request:
        """Queue a request, or raise :class:`QueueFull` (bounded queue) /
        ``ValueError`` (oversized for the configured context window)."""
        need = len(request.prompt) + request.max_new_tokens
        if need > self.max_context:
            raise ValueError(
                f"request {request.id}: prompt {len(request.prompt)} + "
                f"max_new {request.max_new_tokens} exceeds the "
                f"max_context window {self.max_context}")
        with self.lock:
            if len(self.waiting) >= self.max_queue:
                self.rejected += 1
                raise QueueFull(
                    f"request queue at capacity ({self.max_queue}); "
                    "retry with backoff")
            self.waiting.append(request)
        return request

    def _admissible(self, request: Request) -> bool:
        return (len(self.active) < self.max_batch
                and self.cache.allocator.can_allocate(
                    blocks_for_tokens(request.total_tokens(),
                                      self.cache.block_size)))

    # --------------------------------------------------------- scheduling
    def schedule(self):
        """One iteration's work: ``(prefills, decodes)``.

        ``prefills``: newly admitted requests (KV blocks now reserved,
        state ACTIVE) for the engine to prefill this step, at most
        ``prefill_per_step``. ``decodes``: every request already active
        BEFORE this call — they get one decode token this step. Prefilled
        requests join the decode batch at the NEXT step (their first token
        comes out of the prefill forward itself)."""
        evicted: List[Request] = []
        with self.lock:
            decodes = list(self.active)
            prefills: List[Request] = []
            i = 0
            while (len(prefills) < self.prefill_per_step
                   and i < len(self.waiting)):
                req = self.waiting[i]
                if (req.deadline_t is not None
                        and time.monotonic() >= req.deadline_t):
                    # past-deadline while still queued: evict instead of
                    # admitting — prefilling it would burn a decode slot
                    # and KV blocks on an answer nobody is waiting for
                    self.waiting.pop(i)
                    self.cancelled += 1
                    evicted.append(req)
                    continue
                if self._admissible(req):
                    self.waiting.pop(i)
                    self.cache.allocate(req.id, req.total_tokens())
                    req.admitted_t = time.monotonic()
                    req.state = ACTIVE
                    self.active.append(req)
                    prefills.append(req)
                elif self.strict_fifo:
                    break  # the queue head waits; nobody overtakes it
                else:
                    i += 1
        # finish() fires completion callbacks (result delivery — possibly
        # a blocking socket send): outside the lock, like complete()/sweep()
        for req in evicted:
            req.finish(CANCELLED, "deadline exceeded in queue")
        return prefills, decodes

    # --------------------------------------------------------- completion
    def complete(self, request: Request, state: str = DONE,
                 error: str = "") -> None:
        """Retire a request: free its KV blocks, update counters, fire its
        callback/event."""
        with self.lock:
            if request in self.active:
                self.active.remove(request)
            if request.id in self.cache.requests():
                self.cache.free(request.id)
            if state == DONE:
                self.completed += 1
            elif state == CANCELLED:
                self.cancelled += 1
            else:
                self.failed += 1
        request.finish(state, error)

    # ------------------------------------------------- cancellation / TTL
    def cancel(self, request_id: str, reason: str = "cancelled"
               ) -> Optional[Request]:
        """Cancel one request by id wherever it sits (queued or active),
        freeing its KV reservation. Returns the request, or None when the
        id is unknown (already finished — cancels race results by design).

        Callers on the engine thread may invoke this directly; other
        threads should route through ``ServingEngine.cancel`` so the
        eviction lands between engine steps, never mid-forward."""
        found: Optional[Request] = None
        with self.lock:
            for req in self.waiting:
                if req.id == request_id:
                    self.waiting.remove(req)
                    self.cancelled += 1
                    found = req
                    break
            if found is None:
                for req in self.active:
                    if req.id == request_id:
                        self.active.remove(req)
                        if req.id in self.cache.requests():
                            self.cache.free(req.id)
                        self.cancelled += 1
                        found = req
                        break
        if found is not None:
            # callback runs outside the lock (see schedule()/complete())
            found.finish(CANCELLED, reason)
        return found

    def sweep(self) -> Tuple[List[Request], List[Request]]:
        """One pass of the lifetime/deadline sweep: evict every request
        past its wire deadline and every request older than
        ``request_ttl``. Returns ``(expired, deadline_missed)`` — both
        already finished CANCELLED with their KV blocks back in the pool."""
        now = time.monotonic()
        expired: List[Request] = []
        missed: List[Request] = []
        with self.lock:
            for req in list(self.waiting) + list(self.active):
                if (self.request_ttl is not None
                        and now - req.submitted_t >= self.request_ttl):
                    expired.append(req)
                elif (req.deadline_t is not None and now >= req.deadline_t):
                    missed.append(req)
            for req in expired + missed:
                if req in self.waiting:
                    self.waiting.remove(req)
                if req in self.active:
                    self.active.remove(req)
                if req.id in self.cache.requests():
                    self.cache.free(req.id)
        for req in expired:
            self.expired += 1
            req.finish(CANCELLED, "request ttl %.1fs exceeded"
                       % self.request_ttl)
        for req in missed:
            self.cancelled += 1
            req.finish(CANCELLED, "deadline exceeded")
        return expired, missed

    # ------------------------------------------------------------- status
    def queue_depth(self) -> int:
        with self.lock:
            return len(self.waiting)

    def active_count(self) -> int:
        with self.lock:
            return len(self.active)

    def has_work(self) -> bool:
        with self.lock:
            return bool(self.waiting or self.active)

    def evict_queued(self) -> List[Request]:
        """Remove every still-queued (not yet admitted) request WITHOUT
        finishing it. The draining serving worker hands these back to the
        frontend as retryable ``SERVE_REJECTED`` so they re-dispatch to
        another replica — from the client's point of view they were never
        here. Active requests are untouched: a drain finishes in-flight
        work."""
        with self.lock:
            evicted = list(self.waiting)
            self.waiting = []
        return evicted

    def drain(self, error: str) -> List[Request]:
        """Fail everything queued or active (engine shutdown); returns the
        drained requests."""
        with self.lock:
            doomed = self.waiting + self.active
            self.waiting = []
        for req in doomed:
            self.complete(req, FAILED, error)
        return doomed
