"""Serving client: submit requests to a frontend, survive every outage.

The client's recovery contract is deliberately dumb: it remembers the
encoded SUBMIT payload of every unresolved request, and whenever the
connection to the frontend (``serving/server.py``) is re-established it
blindly resubmits all of them. Correctness comes from the frontend, not
the client — request ids are client-chosen and the frontend dedupes on
them (in-flight resubmits re-own the request, finished ones answer from
the result cache), so the naive replay is exactly-once end to end.

Admission backpressure (``SERVE_REJECTED``) is retried here with capped
exponential backoff per request, invisible to the caller unless
``max_retries`` runs out.
"""

from __future__ import annotations

import itertools
import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from ..runtime import wire

logger = logging.getLogger("horovod_tpu")


class ClientRequest:
    """Future for one submitted request."""

    __slots__ = ("id", "tokens", "error", "latency", "rejections",
                 "submitted_t", "done_t", "_event", "_failed")

    def __init__(self, request_id: str):
        self.id = request_id
        self.tokens: List[int] = []
        self.error = ""
        self.latency = 0.0        # frontend-measured dispatch-to-done
        self.rejections = 0       # backpressure retries absorbed
        self.submitted_t = time.monotonic()
        self.done_t: Optional[float] = None
        self._event = threading.Event()
        self._failed = False

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.id} not done")
        if self._failed:
            raise RuntimeError(f"request {self.id} failed: {self.error}")
        return list(self.tokens)

    def client_latency(self) -> Optional[float]:
        """Submit-to-result wall time as this client saw it (includes
        queueing, retries and any reconnect windows)."""
        if self.done_t is None:
            return None
        return self.done_t - self.submitted_t


class ServingClient:
    """One connection to a serving frontend."""

    _ids = itertools.count()

    def __init__(self, host: str, port: int, name: str = "client",
                 secret: Optional[str] = None, max_retries: int = 64,
                 connect_timeout: float = 30.0):
        self.host = host
        self.port = int(port)
        self.name = name
        self.secret = (secret if secret is not None
                       else os.environ.get("HVD_SECRET", ""))
        self.max_retries = int(max_retries)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        # rid -> (future, encoded SUBMIT payload) for every unresolved
        # request — the replay set for reconnects
        self._pending: Dict[str, tuple] = {}
        self._connect(deadline=time.monotonic() + connect_timeout)
        self._reader = threading.Thread(target=self._read_loop,
                                        name="hvd-serve-client",
                                        daemon=True)
        self._reader.start()

    # ------------------------------------------------------------- wire
    def _connect(self, deadline: Optional[float] = None) -> None:
        delay = 0.1
        while not self._stop.is_set():
            try:
                sock = socket.create_connection((self.host, self.port),
                                                timeout=5.0)
                sock.settimeout(1.0)
                wire.send_frame(sock, self.secret, wire.MSG_SERVE_HELLO,
                                0, -1,
                                wire.encode_serve_hello(
                                    wire.SERVE_ROLE_CLIENT, self.name, 0))
                with self._lock:
                    self._sock = sock
                    replay = [p for _, p in self._pending.values()]
                for payload in replay:
                    self._send(wire.MSG_SERVE_SUBMIT, payload)
                return
            except OSError as exc:
                if deadline is not None and time.monotonic() > deadline:
                    raise ConnectionError(
                        f"serving frontend {self.host}:{self.port} "
                        f"unreachable: {exc}")
                if self._stop.wait(delay):
                    raise ConnectionError("client closed while connecting")
                delay = min(delay * 2, 2.0)
        raise ConnectionError("client closed while connecting")

    def _send(self, msg_type: int, payload: bytes) -> bool:
        with self._lock:
            sock = self._sock
            if sock is None:
                return False
            try:
                self._seq += 1
                wire.send_frame(sock, self.secret, msg_type, self._seq, -1,
                                payload)
                return True
            except OSError:
                return False

    def _read_loop(self) -> None:
        while not self._stop.is_set():
            sock = self._sock
            if sock is None:
                try:
                    self._connect()
                except ConnectionError:
                    return
                continue
            try:
                frame = wire.recv_frame(sock, self.secret, self._stop)
            except wire.ShutdownError:
                return
            except (ConnectionError, OSError):
                if self._stop.is_set():
                    return
                logger.info("client %s: frontend connection lost; "
                            "reconnecting and resubmitting %d request(s)",
                            self.name, len(self._pending))
                with self._lock:
                    self._sock = None
                continue
            if frame.msg_type == wire.MSG_SERVE_RESULT:
                self._on_result(frame.payload)

    # ----------------------------------------------------------- results
    def _on_result(self, payload: bytes) -> None:
        rid, status, tokens, error, latency = \
            wire.decode_serve_result(payload)
        with self._lock:
            entry = self._pending.get(rid)
        if entry is None:
            return
        fut, submit_payload = entry
        if status == wire.SERVE_REJECTED:
            fut.rejections += 1
            if fut.rejections <= self.max_retries:
                delay = min(0.05 * (2 ** min(fut.rejections, 6)), 2.0)
                timer = threading.Timer(
                    delay, lambda: self._send(wire.MSG_SERVE_SUBMIT,
                                              submit_payload))
                timer.daemon = True
                timer.start()
                return
            error = error or "rejected; retry budget exhausted"
            status = wire.SERVE_FAILED
        with self._lock:
            self._pending.pop(rid, None)
        fut.tokens = tokens
        fut.error = error
        fut.latency = latency
        fut._failed = status != wire.SERVE_OK
        fut.done_t = time.monotonic()
        fut._event.set()

    # ------------------------------------------------------------ public
    def submit(self, prompt: List[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               request_id: Optional[str] = None) -> ClientRequest:
        rid = (request_id if request_id is not None
               else f"{self.name}-{next(ServingClient._ids)}")
        payload = wire.encode_serve_submit(rid, prompt, max_new_tokens,
                                           eos_id)
        fut = ClientRequest(rid)
        with self._lock:
            self._pending[rid] = (fut, payload)
        # a failed send is fine: the reconnect replay will carry it
        self._send(wire.MSG_SERVE_SUBMIT, payload)
        return fut

    def generate(self, prompt: List[int], max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = 120.0) -> List[int]:
        return self.submit(prompt, max_new_tokens,
                           eos_id=eos_id).result(timeout)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._reader.join(timeout=5)
