"""Serving client: submit requests to a frontend, survive every outage.

The client's recovery contract is deliberately dumb: it remembers the
encoded SUBMIT payload of every unresolved request, and whenever the
connection to the frontend (``serving/server.py``) is re-established it
blindly resubmits all of them. Correctness comes from the frontend, not
the client — request ids are client-chosen and the frontend dedupes on
them (in-flight resubmits re-own the request, finished ones answer from
the result cache), so the naive replay is exactly-once end to end. The
same replay carries requests across a frontend *failover*: when redials
keep failing the client probes the rendezvous KV for
``serve.addr.{gen}.f{n}`` (a promoted standby) and replays there —
the standby's replicated result LRU dedupes requests the old frontend
already answered.

Admission backpressure (``SERVE_REJECTED``) is retried here with capped
exponential backoff per request, invisible to the caller unless
``max_retries`` runs out. ``SERVE_SHED`` (overload, best-effort class) and
``SERVE_CANCELLED`` are terminal by design — retrying into an overload
makes it worse, and a cancel is an answer.

Cancellation propagates from here too: ``result(timeout)`` expiry sends a
``MSG_SERVE_CANCEL`` upstream before raising (the frontend tombstones the
request and the worker frees its KV blocks), and :meth:`close` cancels
everything still unresolved — an abandoned client never strands resources
on the serving pod.
"""

from __future__ import annotations

import itertools
import logging
import os
import socket
import threading
import time
import zlib
from typing import Dict, List, Optional

from ..runtime import wire
from ..runtime.coordinator import _backoff_schedule, _resolve_key

logger = logging.getLogger("horovod_tpu")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    return float(raw) if raw else default


class ClientRequest:
    """Future for one submitted request."""

    __slots__ = ("id", "tokens", "error", "latency", "rejections",
                 "submitted_t", "done_t", "status", "_event", "_failed",
                 "_cancel")

    def __init__(self, request_id: str, cancel=None):
        self.id = request_id
        self.tokens: List[int] = []
        self.error = ""
        self.latency = 0.0        # frontend-measured dispatch-to-done
        self.rejections = 0       # backpressure retries absorbed
        self.submitted_t = time.monotonic()
        self.done_t: Optional[float] = None
        self.status = -1          # wire.SERVE_* once done
        self._event = threading.Event()
        self._failed = False
        self._cancel = cancel     # owning client's cancel hook

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._event.wait(timeout):
            # the caller stopped waiting: propagate the cancel upstream so
            # the pod stops spending decode slots and KV blocks on an
            # answer nobody will read
            if self._cancel is not None:
                self._cancel(self.id, "client timeout")
            raise TimeoutError(f"request {self.id} not done")
        if self._failed:
            raise RuntimeError(f"request {self.id} failed: {self.error}")
        return list(self.tokens)

    def client_latency(self) -> Optional[float]:
        """Submit-to-result wall time as this client saw it (includes
        queueing, retries and any reconnect windows)."""
        if self.done_t is None:
            return None
        return self.done_t - self.submitted_t


class ServingClient:
    """One connection to a serving frontend."""

    _ids = itertools.count()

    def __init__(self, host: str, port: int, name: str = "client",
                 secret: Optional[str] = None, max_retries: int = 64,
                 connect_timeout: float = 30.0, gen: int = 0):
        self.host = host
        self.port = int(port)
        self.name = name
        self.gen = int(gen)
        self.secret = (secret if secret is not None
                       else os.environ.get("HVD_SECRET", ""))
        self.max_retries = int(max_retries)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        # deterministic jitter identity: clients have no rank, so hash the
        # name — distinct clients spread, the same client reproduces
        self._jitter_id = zlib.crc32(name.encode()) & 0x7FFFFFFF
        self._guard = wire.FenceGuard(rank=-1)
        self._fo = 0
        # rid -> (future, encoded SUBMIT payload) for every unresolved
        # request — the replay set for reconnects
        self._pending: Dict[str, tuple] = {}
        self._connect(deadline=time.monotonic() + connect_timeout)
        self._reader = threading.Thread(target=self._read_loop,
                                        name="hvd-serve-client",
                                        daemon=True)
        self._reader.start()

    # ------------------------------------------------------------- wire
    def _probe_failover(self) -> None:
        """Look for a promoted standby frontend under the serving failover
        key; re-aim and learn the new fencing epoch when found."""
        try:
            addr, secret = _resolve_key(
                f"serve.addr.{self.gen}.f{self._fo + 1}", timeout=0.3)
        except Exception:
            return
        self._fo += 1
        from ..runtime import lease as _lease

        if _lease.lease_enabled():
            self._guard.observe(_lease.read_lease_epoch(
                self.gen, key=f"serve.lease.{self.gen}"))
        host, port = addr.rsplit(":", 1)
        self.host, self.port = host, int(port)
        if secret:
            self.secret = secret
        logger.warning("client %s: following serving frontend failover "
                       "#%d to %s", self.name, self._fo, addr)

    def _connect(self, deadline: Optional[float] = None) -> None:
        attempt = 0
        jitter = _env_float("HOROVOD_RECONNECT_JITTER", 0.0)
        while not self._stop.is_set():
            attempt += 1
            try:
                sock = socket.create_connection((self.host, self.port),
                                                timeout=5.0)
                sock.settimeout(1.0)
                wire.send_frame(sock, self.secret, wire.MSG_SERVE_HELLO,
                                0, -1,
                                wire.encode_serve_hello(
                                    wire.SERVE_ROLE_CLIENT, self.name, 0),
                                fence=self._guard.epoch)
                with self._lock:
                    self._sock = sock
                    replay = [p for _, p in self._pending.values()]
                for payload in replay:
                    self._send(wire.MSG_SERVE_SUBMIT, payload)
                return
            except OSError as exc:
                if attempt >= 2:
                    self._probe_failover()
                if deadline is not None and time.monotonic() > deadline:
                    raise ConnectionError(
                        f"serving frontend {self.host}:{self.port} "
                        f"unreachable: {exc}")
                delay = _backoff_schedule(self._jitter_id, attempt, 0.1,
                                          2.0, jitter)
                if self._stop.wait(delay):
                    raise ConnectionError("client closed while connecting")
        raise ConnectionError("client closed while connecting")

    def _send(self, msg_type: int, payload: bytes) -> bool:
        with self._lock:
            sock = self._sock
            if sock is None:
                return False
            try:
                self._seq += 1
                wire.send_frame(sock, self.secret, msg_type, self._seq, -1,
                                payload, fence=self._guard.epoch)
                return True
            except OSError:
                return False

    def _read_loop(self) -> None:
        while not self._stop.is_set():
            sock = self._sock
            if sock is None:
                try:
                    self._connect()
                except ConnectionError:
                    return
                continue
            try:
                frame = wire.recv_frame(sock, self.secret, self._stop,
                                        guard=self._guard)
            except wire.ShutdownError:
                return
            except (ConnectionError, OSError):
                # FenceError lands here too: a deposed frontend's frames
                # cut the connection, and the reconnect finds the new one
                if self._stop.is_set():
                    return
                logger.info("client %s: frontend connection lost; "
                            "reconnecting and resubmitting %d request(s)",
                            self.name, len(self._pending))
                with self._lock:
                    self._sock = None
                continue
            if frame.msg_type == wire.MSG_SERVE_RESULT:
                self._on_result(frame.payload)

    # ----------------------------------------------------------- results
    def _on_result(self, payload: bytes) -> None:
        rid, status, tokens, error, latency = \
            wire.decode_serve_result(payload)
        with self._lock:
            entry = self._pending.get(rid)
        if entry is None:
            return
        fut, submit_payload = entry
        if status == wire.SERVE_REJECTED:
            fut.rejections += 1
            if fut.rejections <= self.max_retries:
                delay = min(0.05 * (2 ** min(fut.rejections, 6)), 2.0)
                timer = threading.Timer(
                    delay, lambda: self._send(wire.MSG_SERVE_SUBMIT,
                                              submit_payload))
                timer.daemon = True
                timer.start()
                return
            error = error or "rejected; retry budget exhausted"
            status = wire.SERVE_FAILED
        # SERVE_SHED and SERVE_CANCELLED fall through as terminal: a shed
        # retried into the same overload only deepens it (the caller owns
        # any re-try policy), and a cancel IS the answer
        with self._lock:
            self._pending.pop(rid, None)
        fut.tokens = tokens
        fut.error = error
        fut.latency = latency
        fut.status = status
        fut._failed = status != wire.SERVE_OK
        fut.done_t = time.monotonic()
        fut._event.set()

    # ------------------------------------------------------------ public
    def submit(self, prompt: List[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               request_id: Optional[str] = None,
               deadline: Optional[float] = None,
               priority: int = wire.SERVE_PRIO_HIGH) -> ClientRequest:
        """Submit one generation request. ``deadline`` is an end-to-end
        budget in seconds carried on the wire — each hop re-anchors it on
        its own clock and evicts the request once it expires; ``priority``
        selects the overload class (``wire.SERVE_PRIO_BEST_EFFORT`` is
        shed/browned-out first)."""
        rid = (request_id if request_id is not None
               else f"{self.name}-{next(ServingClient._ids)}")
        payload = wire.encode_serve_submit(rid, prompt, max_new_tokens,
                                           eos_id, deadline or 0.0,
                                           priority)
        fut = ClientRequest(rid, cancel=self.cancel)
        with self._lock:
            self._pending[rid] = (fut, payload)
        # a failed send is fine: the reconnect replay will carry it
        self._send(wire.MSG_SERVE_SUBMIT, payload)
        return fut

    def cancel(self, request_id: str, reason: str = "cancelled") -> bool:
        """Cancel one unresolved request: drop it locally (the future
        fails with the reason) and tell the frontend so the pod reclaims
        its resources. False when the id is unknown/already done."""
        with self._lock:
            entry = self._pending.pop(request_id, None)
        if entry is None:
            return False
        fut, _ = entry
        self._send(wire.MSG_SERVE_CANCEL,
                   wire.encode_serve_cancel(request_id, reason))
        fut.error = reason
        fut.status = wire.SERVE_CANCELLED
        fut._failed = True
        fut.done_t = time.monotonic()
        fut._event.set()
        return True

    def generate(self, prompt: List[int], max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = 120.0) -> List[int]:
        return self.submit(prompt, max_new_tokens,
                           eos_id=eos_id).result(timeout)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def close(self) -> None:
        # walking away with requests still open would strand decode work
        # and KV blocks on the pod until the TTL sweep: cancel them first
        with self._lock:
            unresolved = list(self._pending)
        for rid in unresolved:
            self.cancel(rid, "client closed")
        self._stop.set()
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._reader.join(timeout=5)
