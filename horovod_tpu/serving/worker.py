"""Serving worker: one model replica dialing the frontend.

A worker owns a :class:`~.engine.ServingEngine` and a single control-plane
connection to the frontend (``serving/server.py``). The protocol from the
worker's side:

1. connect, ``MSG_SERVE_HELLO(role=worker, name, capacity=max_batch)``;
2. ``MSG_SERVE_SUBMIT`` frames feed :meth:`ServingEngine.submit`; each
   request's completion callback ships ``MSG_SERVE_RESULT`` back;
3. ``MSG_SERVE_CANCEL`` frames evict the request between engine steps
   (KV blocks back in the pool within one scheduler sweep); a
   ``MSG_SERVE_DRAIN`` quiesces the replica — queued work is handed back
   as retryable ``SERVE_REJECTED`` (the frontend re-dispatches it), new
   submits are refused, in-flight generations run to completion;
4. heartbeats (``MSG_HEARTBEAT``) every ``HOROVOD_HEARTBEAT_INTERVAL`` and
   ``MSG_METRICS`` registry snapshots every ``HOROVOD_METRICS_INTERVAL``
   keep the frontend's liveness and pod ``/metrics`` views current.

Recovery mirrors the PR-4 worker-side control plane: a dropped connection
triggers reconnect with deterministic per-replica jittered backoff
(``HOROVOD_RECONNECT_JITTER`` — a mass reconnect after a frontend death
must not land as one synchronized herd on the promoted standby); in-flight
generations keep running through the outage, their results park in an
unsent list and replay after reconnect (the frontend dedupes by request
id, so replaying a result the frontend already re-admitted elsewhere is
harmless). When redials keep failing the worker probes the rendezvous KV
for ``serve.addr.{gen}.f{n}`` — a promoted standby frontend — re-aims at
it, and seeds its :class:`~..runtime.wire.FenceGuard` from
``serve.lease.{gen}`` so the deposed frontend's frames are rejected from
the first exchange with the new leader.

``python -m horovod_tpu.serving.worker --addr HOST:PORT`` is the replica
entry point the CI pod-smoke and the chaos drills spawn; every replica
builds the identical deterministic tiny model from a fixed PRNG seed,
standing in for "every replica restored the same checkpoint".
"""

from __future__ import annotations

import argparse
import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import blackbox as _blackbox
from ..metrics import local_snapshot
from ..runtime import wire
from ..runtime.coordinator import (MSG_HEARTBEAT, MSG_METRICS,
                                   _backoff_schedule, _resolve_key)
from .engine import ServingConfig, ServingEngine
from .scheduler import CANCELLED, DONE, QueueFull, Request

logger = logging.getLogger("horovod_tpu")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    return float(raw) if raw else default


class ServingWorker:
    """Runs one engine replica against a frontend address."""

    def __init__(self, host: str, port: int, engine: ServingEngine,
                 name: str = "worker-0", rank: int = 0,
                 secret: Optional[str] = None, gen: int = 0):
        self.host = host
        self.port = int(port)
        self.engine = engine
        self.name = name
        self.rank = int(rank)
        self.gen = int(gen)
        self.secret = (secret if secret is not None
                       else os.environ.get("HVD_SECRET", ""))
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._seq = 0
        # request id -> encoded RESULT payload not yet delivered (either
        # the connection was down at completion, or the send failed)
        self._unsent: Dict[str, bytes] = {}
        self._unsent_lock = threading.Lock()
        self._seen: Dict[str, bool] = {}  # dedupe of in-flight resubmits
        self._guard = wire.FenceGuard(rank=self.rank)
        self._fo = 0          # failover addresses consumed so far
        self.draining = False
        self._last_saturation = 0.0

    # -------------------------------------------------------------- wire
    def _send(self, msg_type: int, payload: bytes) -> bool:
        sock = self._sock
        if sock is None:
            return False
        try:
            with self._send_lock:
                self._seq += 1
                wire.send_frame(sock, self.secret, msg_type, self._seq,
                                self.rank, payload,
                                fence=self._guard.epoch)
            return True
        except OSError:
            return False

    def _probe_failover(self) -> None:
        """The dead frontend may have left a promoted standby behind: look
        for the next serving failover address with a short timeout and,
        when published, re-aim every further dial at it — learning the new
        fencing epoch first, so the deposed frontend's frames are rejected
        from here on."""
        try:
            addr, secret = _resolve_key(
                f"serve.addr.{self.gen}.f{self._fo + 1}", timeout=0.3)
        except Exception:
            return  # nothing promoted (yet); keep redialing the old address
        self._fo += 1
        from ..runtime import lease as _lease

        if _lease.lease_enabled():
            self._guard.observe(_lease.read_lease_epoch(
                self.gen, key=f"serve.lease.{self.gen}"))
        host, port = addr.rsplit(":", 1)
        self.host, self.port = host, int(port)
        if secret:
            self.secret = secret
        logger.warning("worker %s: following serving frontend failover "
                       "#%d to %s (fence epoch %d)", self.name, self._fo,
                       addr, self._guard.epoch)

    def _connect(self) -> socket.socket:
        """Dial + HELLO with capped, per-replica-jittered exponential
        backoff, forever (the frontend may be restarting — serving workers
        outlive it). Failed attempts probe the KV for a promoted standby."""
        jitter = _env_float("HOROVOD_RECONNECT_JITTER", 0.0)
        attempt = 0
        while not self._stop.is_set():
            attempt += 1
            try:
                sock = socket.create_connection((self.host, self.port),
                                                timeout=5.0)
                sock.settimeout(1.0)
                wire.send_frame(
                    sock, self.secret, wire.MSG_SERVE_HELLO, 0, self.rank,
                    wire.encode_serve_hello(wire.SERVE_ROLE_WORKER,
                                            self.name,
                                            self.engine.config.max_batch),
                    fence=self._guard.epoch)
                # a drain is scoped to the frontend session that issued
                # it; this HELLO opened a new session (possibly with a
                # promoted standby that knows nothing of the drain), so
                # the replica serves again
                self.draining = False
                return sock
            except OSError as exc:
                if attempt >= 2:
                    self._probe_failover()
                delay = _backoff_schedule(self.rank, attempt, 0.1, 5.0,
                                          jitter)
                logger.info("worker %s: frontend unreachable (%s); "
                            "retrying in %.2fs", self.name, exc, delay)
                if self._stop.wait(delay):
                    break
        raise wire.ShutdownError("serving worker stopped")

    # ---------------------------------------------------------- requests
    def _on_submit(self, payload: bytes) -> None:
        (rid, prompt, max_new, eos, deadline,
         _priority) = wire.decode_serve_submit_ex(payload)
        if self.draining:
            # quiesced: hand the request straight back for re-dispatch
            self._queue_result(rid, wire.encode_serve_result(
                rid, wire.SERVE_REJECTED, [], "worker draining"))
            return
        with self._unsent_lock:
            if rid in self._seen:
                # duplicate dispatch (frontend resend race): the original
                # submission's callback / unsent replay will answer
                return
            self._seen[rid] = True
            if len(self._seen) > 8192:
                for k in list(self._seen)[:4096]:
                    del self._seen[k]
        try:
            self.engine.submit(prompt, max_new, request_id=rid,
                               eos_id=eos, callback=self._on_done,
                               deadline=deadline or None)
        except QueueFull:
            self._record_saturation()
            with self._unsent_lock:
                # handing the request back: forget the id, or the
                # frontend's re-dispatch of this retryable rejection
                # would be swallowed as a duplicate (mirrors _on_drain)
                self._seen.pop(rid, None)
            self._queue_result(rid, wire.encode_serve_result(
                rid, wire.SERVE_REJECTED, [],
                "replica queue full"))
        except ValueError as exc:
            self._queue_result(rid, wire.encode_serve_result(
                rid, wire.SERVE_FAILED, [], str(exc)))

    def _record_saturation(self) -> None:
        """Rate-limited blackbox breadcrumb naming WHICH resource is the
        bottleneck — the doctor's serving_overload evidence."""
        now = time.monotonic()
        if now - self._last_saturation < 1.0:
            return
        self._last_saturation = now
        _blackbox.record(
            _blackbox.K_ANOMALY, "serving_saturation",
            "replica %s saturated resource=%s"
            % (self.name, self.engine.saturated_resource()),
            rank=self.rank)

    def _on_cancel(self, payload: bytes) -> None:
        rid, reason = wire.decode_serve_cancel(payload)
        # evicted between engine steps; KV blocks return to the pool
        # within one scheduler sweep
        self.engine.cancel(rid, reason or "cancelled by frontend")
        with self._unsent_lock:
            # a parked result for a cancelled request would replay as
            # noise the frontend already tombstoned — drop it
            self._unsent.pop(rid, None)

    def _on_drain(self, payload: bytes) -> None:
        reason = wire.decode_serve_drain(payload)
        self.draining = True
        evicted = self.engine.scheduler.evict_queued()
        logger.warning(
            "worker %s: draining (%s) — %d queued request(s) handed back, "
            "%d in-flight running to completion", self.name, reason,
            len(evicted), self.engine.scheduler.active_count())
        with self._unsent_lock:
            for req in evicted:
                # forget the id so a post-drain restart of this replica
                # can accept a re-dispatch of the same request
                self._seen.pop(req.id, None)
        for req in evicted:
            self._queue_result(req.id, wire.encode_serve_result(
                req.id, wire.SERVE_REJECTED, [],
                "worker draining: requeue"))

    def _on_done(self, req: Request) -> None:
        if req.state == DONE:
            payload = wire.encode_serve_result(
                req.id, wire.SERVE_OK, req.output, "",
                req.latency() or 0.0)
        elif req.state == CANCELLED:
            payload = wire.encode_serve_result(
                req.id, wire.SERVE_CANCELLED, [], req.error)
        else:
            payload = wire.encode_serve_result(
                req.id, wire.SERVE_FAILED, [], req.error)
        self._queue_result(req.id, payload)

    def _queue_result(self, rid: str, payload: bytes) -> None:
        with self._unsent_lock:
            self._unsent[rid] = payload
        self._flush_results()

    def _flush_results(self) -> None:
        with self._unsent_lock:
            items: List[Tuple[str, bytes]] = list(self._unsent.items())
        for rid, payload in items:
            if not self._send(wire.MSG_SERVE_RESULT, payload):
                return  # connection down; replay after reconnect
            with self._unsent_lock:
                self._unsent.pop(rid, None)

    # ---------------------------------------------------------- heartbeat
    def _heartbeat_loop(self) -> None:
        hb = _env_float("HOROVOD_HEARTBEAT_INTERVAL", 5.0)
        metrics_every = _env_float("HOROVOD_METRICS_INTERVAL", 10.0)
        last_metrics = 0.0
        while not self._stop.wait(min(hb, 1.0)):
            self._send(MSG_HEARTBEAT, b"")
            now = time.monotonic()
            if now - last_metrics >= metrics_every:
                last_metrics = now
                self._send(MSG_METRICS, wire.encode_metrics_report(
                    self.rank, time.time(), local_snapshot()))

    # ----------------------------------------------------------- run loop
    def run(self) -> None:
        """Serve until :meth:`stop`: engine loop + heartbeats in the
        background, this thread reading frontend frames (reconnecting on
        every connection failure — including fence rejections of a
        deposed frontend's traffic, which surface as FrameErrors and land
        back here to redial the promoted one)."""
        self.engine.start()
        _blackbox.maybe_activate()
        hb = threading.Thread(target=self._heartbeat_loop,
                              name="hvd-serve-worker-hb", daemon=True)
        hb.start()
        try:
            while not self._stop.is_set():
                try:
                    self._sock = self._connect()
                except wire.ShutdownError:
                    return
                logger.info("worker %s connected to frontend", self.name)
                self._flush_results()  # replay results from the outage
                try:
                    while not self._stop.is_set():
                        frame = wire.recv_frame(self._sock, self.secret,
                                                self._stop,
                                                guard=self._guard)
                        if frame.msg_type == wire.MSG_SERVE_SUBMIT:
                            self._on_submit(frame.payload)
                        elif frame.msg_type == wire.MSG_SERVE_CANCEL:
                            self._on_cancel(frame.payload)
                        elif frame.msg_type == wire.MSG_SERVE_DRAIN:
                            self._on_drain(frame.payload)
                except wire.ShutdownError:
                    return
                except (ConnectionError, OSError) as exc:
                    if self._stop.is_set():
                        return
                    logger.warning("worker %s: frontend connection lost "
                                   "(%s); reconnecting", self.name, exc)
                    self._sock = None
        finally:
            self.engine.stop()
            hb.join(timeout=2)

    def start(self) -> "ServingWorker":
        threading.Thread(target=self.run, name="hvd-serve-worker",
                         daemon=True).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


def build_replica_engine(vocab_size: int = 251, num_layers: int = 2,
                         num_heads: int = 2, d_model: int = 64,
                         max_seq_len: int = 128,
                         config: Optional[ServingConfig] = None,
                         seed: int = 0) -> ServingEngine:
    """Deterministic tiny-replica engine: every process that calls this
    with the same arguments holds bit-identical parameters (fixed PRNG
    seed), standing in for 'restored the same checkpoint' in tests,
    benchmarks and the CI pod smoke."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import TransformerLM

    model = TransformerLM(vocab_size=vocab_size, num_layers=num_layers,
                          num_heads=num_heads, d_model=d_model,
                          max_seq_len=max_seq_len)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    cfg = config or ServingConfig(max_context=max_seq_len)
    if cfg.max_context is None or cfg.max_context > max_seq_len:
        cfg.max_context = max_seq_len
    return ServingEngine(model, params, cfg)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="horovod_tpu serving worker replica")
    ap.add_argument("--addr", required=True, help="frontend HOST:PORT")
    ap.add_argument("--name", default=None)
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--gen", type=int, default=0)
    ap.add_argument("--vocab", type=int, default=251)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--max-seq", type=int, default=128)
    ap.add_argument("--blocks", type=int, default=None)
    ap.add_argument("--block-size", type=int, default=None)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--slow", type=float, default=0.0,
                    help="stall every engine step by SLOW seconds "
                         "(slow-replica chaos drill)")
    args = ap.parse_args(argv)
    host, port = args.addr.rsplit(":", 1)
    cfg = ServingConfig(block_size=args.block_size, num_blocks=args.blocks,
                        max_batch=args.max_batch, max_context=args.max_seq)
    engine = build_replica_engine(
        vocab_size=args.vocab, num_layers=args.layers, num_heads=args.heads,
        d_model=args.d_model, max_seq_len=args.max_seq, config=cfg)
    if args.slow > 0:
        engine.step_delay = args.slow
    name = args.name or f"worker-{args.rank}"
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s " + name + " %(message)s")
    _blackbox.maybe_activate()
    worker = ServingWorker(host, int(port), engine, name=name,
                           rank=args.rank, gen=args.gen)
    try:
        worker.run()
    except KeyboardInterrupt:
        worker.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
