"""Serving engine: continuous-batching generation over a paged KV cache.

One engine = one model replica. The engine owns the compiled prefill and
decode programs, the :class:`~.kvcache.PagedKVCache`, and the
:class:`~.scheduler.ContinuousBatchingScheduler`; :meth:`ServingEngine.step`
is one iteration of the serving loop (admit → prefill → batched decode),
and :meth:`start` runs it on a background thread so callers just
:meth:`submit` and wait.

Shape stability is the design invariant: prompts pad to the fixed
``prompt_pad`` bucket, the decode batch pads to the fixed ``max_batch``,
and the KV gather pads to the fixed ``max_context`` — so the engine
compiles exactly TWO programs (one prefill, one decode) and, because
``cached_attention`` masks padding to exactly 0.0 contribution at those
fixed shapes, a request's generated tokens are bit-identical whether it
decodes alone or batched with any mix of neighbors (asserted by
tests/test_serving.py).

Tensor parallelism rides the training shardings: pass ``mesh=`` (a
``parallel/tensor.py`` dp×tp mesh) and the engine places the parameters
with ``shard_params_tp`` before compiling — GSPMD inserts the row-parallel
psums in the serving forward exactly as it does in the train step.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import List, Optional

import numpy as np

from ..metrics import instruments
from .kvcache import PagedKVCache
from .scheduler import (ACTIVE, CANCELLED, DONE, FAILED,
                        ContinuousBatchingScheduler, QueueFull, Request)

__all__ = ["ServingConfig", "ServingEngine", "QueueFull", "Request"]

logger = logging.getLogger("horovod_tpu")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else default


class ServingConfig:
    """Engine knobs (env defaults in parentheses; docs/knobs.md):

    * ``block_size`` — KV-cache block granularity in tokens
      (``HOROVOD_SERVING_BLOCK_SIZE``, 16).
    * ``num_blocks`` — KV pool size in blocks
      (``HOROVOD_SERVING_BLOCKS``, 256). Pool bytes per layer =
      ``2 * num_blocks * block_size * d_model * dtype_bytes``.
    * ``max_batch`` — decode-batch width, the max concurrent in-flight
      requests (``HOROVOD_SERVING_MAX_BATCH``, 8).
    * ``max_queue`` — bounded admission queue
      (``HOROVOD_SERVING_MAX_QUEUE``, 128).
    * ``max_context`` — per-request token window, prompt + generated
      (``HOROVOD_SERVING_MAX_CONTEXT``, default the model's
      ``max_seq_len``); also the fixed KV gather width.
    * ``prefill_per_step`` — admissions per engine iteration (1).
    * ``eos_id`` — generation stop token (None = length-only).
    """

    def __init__(self, block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 max_context: Optional[int] = None,
                 prefill_per_step: int = 1,
                 eos_id: Optional[int] = None,
                 cache_dtype=np.float32):
        self.block_size = (block_size if block_size is not None
                           else _env_int("HOROVOD_SERVING_BLOCK_SIZE", 16))
        self.num_blocks = (num_blocks if num_blocks is not None
                           else _env_int("HOROVOD_SERVING_BLOCKS", 256))
        self.max_batch = (max_batch if max_batch is not None
                          else _env_int("HOROVOD_SERVING_MAX_BATCH", 8))
        self.max_queue = (max_queue if max_queue is not None
                          else _env_int("HOROVOD_SERVING_MAX_QUEUE", 128))
        self.max_context = max_context  # None: resolved from the model
        self.prefill_per_step = int(prefill_per_step)
        self.eos_id = eos_id
        self.cache_dtype = cache_dtype


class ServingEngine:
    """Continuous-batching generation engine for one model replica."""

    def __init__(self, model, params, config: Optional[ServingConfig] = None,
                 mesh=None, tp_axis: str = "tp"):
        import jax

        self.model = model
        self.config = cfg = config or ServingConfig()
        if cfg.max_context is None:
            cfg.max_context = int(model.max_seq_len)
        if cfg.max_context > int(model.max_seq_len):
            raise ValueError(
                f"max_context {cfg.max_context} exceeds the model's "
                f"max_seq_len {model.max_seq_len}")
        # the fixed prompt bucket: prompts pad to one compiled width
        self.prompt_pad = cfg.max_context
        head_dim = model.d_model // model.num_heads
        self.cache = PagedKVCache(
            cfg.num_blocks, cfg.block_size, model.num_layers,
            model.num_heads, head_dim, dtype=cfg.cache_dtype)
        self.scheduler = ContinuousBatchingScheduler(
            self.cache, max_batch=cfg.max_batch, max_queue=cfg.max_queue,
            max_context=cfg.max_context,
            prefill_per_step=cfg.prefill_per_step)
        if mesh is not None:
            from ..parallel.tensor import shard_params_tp

            params = shard_params_tp(params, mesh, tp_axis)
        self.params = params
        self._jit_prefill = jax.jit(self._prefill_fn)
        self._jit_decode = jax.jit(self._decode_fn)
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = None
        self._tokens_out = 0
        self._started_t = time.monotonic()
        # cancels from connection threads land here and are applied at the
        # top of step() on the engine thread — never mid-forward, so a
        # cancelled request can't be freed between the KV gather and the
        # KV append of the same decode step
        self._cancel_lock = threading.Lock()
        self._cancels: List[tuple] = []
        # fault-injection knob for the slow-replica chaos drill: a fixed
        # stall before every step, making this replica the hedging target
        self.step_delay = float(
            os.environ.get("HOROVOD_SERVING_STEP_DELAY") or 0.0)

    # ---------------------------------------------------- compiled kernels
    def _empty_past(self, batch: int):
        import jax.numpy as jnp

        m = self.model
        shape = (m.num_layers, batch, 0, m.num_heads,
                 m.d_model // m.num_heads)
        z = jnp.zeros(shape, jnp.float32)
        return z, z, jnp.zeros((batch, 0), bool)

    def _prefill_fn(self, params, tokens):
        """tokens [1, prompt_pad] -> (logits [1, prompt_pad, V],
        k, v [L, 1, prompt_pad, H, Dh])."""
        logits, (nk, nv) = self.model.apply(
            {"params": params}, tokens,
            kv_cache=self._empty_past(tokens.shape[0]))
        return logits, nk, nv

    def _decode_fn(self, params, tokens, past_k, past_v, past_mask, pos):
        """tokens [max_batch, 1], past [L, max_batch, max_context, H, Dh],
        pos [max_batch, 1] -> (next_token [max_batch], logits
        [max_batch, V], k, v [L, max_batch, 1, H, Dh])."""
        import jax.numpy as jnp

        logits, (nk, nv) = self.model.apply(
            {"params": params}, tokens, pos_offset=pos,
            kv_cache=(past_k, past_v, past_mask))
        last = logits[:, -1]
        return jnp.argmax(last, axis=-1).astype(jnp.int32), last, nk, nv

    # ----------------------------------------------------------- requests
    def submit(self, prompt: List[int], max_new_tokens: int,
               request_id: Optional[str] = None,
               eos_id: Optional[int] = None,
               callback=None, deadline: Optional[float] = None) -> Request:
        """Queue one generation request; raises :class:`QueueFull` when the
        admission queue is at capacity and ``ValueError`` when the request
        cannot fit ``max_context``. The returned :class:`Request` is a
        future: ``result(timeout)`` blocks for the generated tokens."""
        if len(prompt) > self.prompt_pad:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the serving "
                f"prompt bucket {self.prompt_pad}")
        req = Request(prompt, max_new_tokens,
                      eos_id=eos_id if eos_id is not None
                      else self.config.eos_id,
                      request_id=request_id, callback=callback,
                      deadline=deadline)
        self.scheduler.submit(req)
        instruments.serving_requests().labels(status="submitted").inc()
        self._observe_gauges()
        self._wake.set()
        return req

    def cancel(self, request_id: str, reason: str = "cancelled") -> None:
        """Request cancellation of ``request_id`` (thread-safe). Applied
        between engine steps; a no-op when the id already finished."""
        with self._cancel_lock:
            self._cancels.append((request_id, reason))
        self._wake.set()

    def saturated_resource(self) -> str:
        """Which resource is the admission bottleneck right now — the
        evidence string the doctor's serving_overload signature names.
        ``decode_slots``: the batch is full; ``kv_blocks``: the paged pool
        cannot fit even one more block; ``queue``: admission is keeping up
        but the bounded submit queue overflowed (burst arrival rate)."""
        sched = self.scheduler
        if sched.active_count() >= sched.max_batch:
            return "decode_slots"
        if not self.cache.allocator.can_allocate(1):
            return "kv_blocks"
        return "queue"

    # ---------------------------------------------------------- main loop
    def step(self) -> bool:
        """One serving iteration: admit + prefill, then one batched decode
        token for every in-flight request. Returns True if any work ran."""
        import jax.numpy as jnp

        if self.step_delay > 0:
            time.sleep(self.step_delay)
        self._apply_cancels()
        prefills, decodes = self.scheduler.schedule()
        did = False
        for req in prefills:
            t0 = time.monotonic()
            self._prefill(req)
            instruments.serving_phase_seconds().labels(phase="prefill") \
                .observe(time.monotonic() - t0)
            did = True
        # requests that finished at prefill (max_new=1 / instant eos) left
        # the active set inside _prefill; decode the remainder
        decodes = [r for r in decodes if r.state == ACTIVE]
        if decodes:
            t0 = time.monotonic()
            self._decode(decodes, jnp)
            instruments.serving_phase_seconds().labels(phase="decode") \
                .observe(time.monotonic() - t0)
            did = True
        if did:
            self._observe_gauges()
        return did

    def _apply_cancels(self) -> None:
        """Between-step cancellation point: apply queued cancels, then one
        deadline/TTL sweep. Runs on the engine thread, so every KV free
        here is ordered against the forward passes."""
        with self._cancel_lock:
            cancels, self._cancels = self._cancels, []
        touched = bool(cancels)
        for rid, reason in cancels:
            if self.scheduler.cancel(rid, reason) is not None:
                instruments.serving_requests().labels(
                    status="cancelled").inc()
                instruments.serving_cancels().labels(
                    reason="propagated").inc()
        expired, missed = self.scheduler.sweep()
        for req in expired:
            instruments.serving_requests().labels(status="expired").inc()
            instruments.serving_cancels().labels(reason="ttl").inc()
        for req in missed:
            instruments.serving_requests().labels(status="cancelled").inc()
            instruments.serving_cancels().labels(reason="deadline").inc()
        if touched or expired or missed:
            self._observe_gauges()

    def _prefill(self, req: Request) -> None:
        import jax.numpy as jnp

        n = len(req.prompt)
        toks = np.zeros((1, self.prompt_pad), np.int32)
        toks[0, :n] = req.prompt
        logits, nk, nv = self._jit_prefill(self.params, jnp.asarray(toks))
        # the prompt's K/V enters the paged pool; pad positions discarded
        self.cache.append(req.id, np.asarray(nk[:, 0, :n]),
                          np.asarray(nv[:, 0, :n]))
        first = int(np.asarray(jnp.argmax(logits[0, n - 1], axis=-1)))
        req.first_token_t = time.monotonic()
        req.output.append(first)
        self._tokens_out += 1
        instruments.serving_tokens().labels(phase="prefill").inc(n)
        instruments.serving_tokens().labels(phase="decode").inc()
        if self._finished(req, first):
            self._complete(req)

    def _decode(self, decodes: List[Request], jnp) -> None:
        b = self.config.max_batch
        ids = [r.id for r in decodes]
        k, v, mask, lengths = self.cache.gather(
            ids + [""] * (b - len(ids)), self.config.max_context)
        toks = np.zeros((b, 1), np.int32)
        pos = np.zeros((b, 1), np.int32)
        for row, req in enumerate(decodes):
            # invariant: the last generated token's K/V is not cached yet —
            # it is this step's input, at position == cached length
            toks[row, 0] = req.output[-1]
            pos[row, 0] = lengths[row]
        next_tok, _, nk, nv = self._jit_decode(
            self.params, jnp.asarray(toks), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(mask), jnp.asarray(pos))
        next_tok = np.asarray(next_tok)
        nk = np.asarray(nk)
        nv = np.asarray(nv)
        instruments.serving_decode_batch().observe(len(decodes))
        for row, req in enumerate(decodes):
            self.cache.append(req.id, nk[:, row], nv[:, row])
            tok = int(next_tok[row])
            req.output.append(tok)
            self._tokens_out += 1
            instruments.serving_tokens().labels(phase="decode").inc()
            if self._finished(req, tok):
                self._complete(req)

    def _finished(self, req: Request, tok: int) -> bool:
        if req.eos_id is not None and tok == req.eos_id:
            return True
        return len(req.output) >= req.max_new_tokens

    def _complete(self, req: Request) -> None:
        self.scheduler.complete(req, DONE)
        lat = req.latency()
        instruments.serving_requests().labels(status="completed").inc()
        instruments.serving_request_latency().labels(stage="total") \
            .observe(lat)
        if req.first_token_t is not None:
            instruments.serving_request_latency().labels(
                stage="first_token").observe(
                req.first_token_t - req.submitted_t)

    def _observe_gauges(self) -> None:
        instruments.serving_queue_depth().set(self.scheduler.queue_depth())
        instruments.serving_active_requests().set(
            self.scheduler.active_count())
        instruments.serving_kv_occupancy().set(self.cache.occupancy())
        instruments.serving_kv_tokens().set(self.cache.used_tokens)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ServingEngine":
        """Run the serving loop on a background thread."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="hvd-serving-engine",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                did = self.step()
            except Exception as exc:  # a broken step fails its requests,
                logger.exception("serving engine step failed")  # not the loop
                for req in self.scheduler.drain(f"engine step failed: {exc}"):
                    instruments.serving_requests().labels(
                        status="failed").inc()
                did = False
            if not did:
                self._wake.wait(0.005)
                self._wake.clear()

    def stop(self, drain_error: str = "engine stopped") -> None:
        self._stop.set()
        self._wake.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)
        for req in self.scheduler.drain(drain_error):
            instruments.serving_requests().labels(status="failed").inc()

    def run_until_idle(self, timeout: float = 60.0) -> None:
        """Drive the loop inline (no background thread) until every
        submitted request completes — the deterministic mode tests and the
        bit-parity assertions use."""
        deadline = time.monotonic() + timeout
        while self.scheduler.has_work():
            if time.monotonic() > deadline:
                raise TimeoutError("serving engine did not go idle")
            self.step()

    # ------------------------------------------------------------- status
    def stats(self) -> dict:
        s = self.scheduler
        return {
            "queue_depth": s.queue_depth(),
            "active": s.active_count(),
            "completed": s.completed,
            "failed": s.failed,
            "rejected": s.rejected,
            "cancelled": s.cancelled,
            "expired": s.expired,
            "kv_blocks_used": self.cache.used_blocks,
            "kv_blocks_total": self.cache.num_blocks,
            "kv_occupancy": round(self.cache.occupancy(), 4),
            "kv_tokens": self.cache.used_tokens,
            "tokens_generated": self._tokens_out,
            "uptime_s": round(time.monotonic() - self._started_t, 3),
        }
