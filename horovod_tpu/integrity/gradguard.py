"""GradGuard: non-finite gradient detection with cross-rank agreement.

One rank emitting a NaN/Inf gradient poisons the allreduce for every rank
(sum/avg of anything with NaN is NaN), and without agreement the ranks
would then disagree on whether to apply the step — the exact replica-
divergence failure the consistency auditor exists to catch. GradGuard
closes the loop *before* the gradient allreduce:

1. **Local detect** — one fused ``isfinite``-all reduction per gradient
   leaf (inexact dtypes only; integers cannot be non-finite).
2. **Cross-rank agreement** — a single small flag allreduce (int32 vector,
   one entry per leaf) so every rank sees the same verdict. Each rank
   contributes a rank bit per offending leaf, so the verdict also names
   the offenders (exact for ranks < 31; larger ranks share bit 31).
3. **Policy** (``HOROVOD_GRAD_GUARD``):
   * ``off``   (default) — no checks, no flag allreduce, zero cost.
   * ``skip``  — drop the optimizer step on EVERY rank (dynamic-loss-
     scale style): replicas stay in lockstep, the batch is lost.
   * ``zero``  — nullify only the offending tensors on every rank and
     apply the rest of the step.
   * ``abort`` — raise :class:`~..exceptions.NonFiniteError` naming
     tensor/rank/step on every rank.

Counters: ``hvd_grad_nonfinite_total`` (offending tensors observed
locally), ``hvd_steps_skipped_total`` (global skip verdicts).

Fault hook: ``nan@grad`` in ``HOROVOD_FAULT_SPEC`` poisons the first leaf
with NaN right before detection, so the whole pillar is drivable from the
chaos harness (docs/fault-tolerance.md).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import List, Tuple

import numpy as np

from .. import basics, faultinject
from ..exceptions import NonFiniteError
from ..metrics import instruments

logger = logging.getLogger("horovod_tpu")

ENV_POLICY = "HOROVOD_GRAD_GUARD"
POLICIES = ("off", "skip", "zero", "abort")

#: verdicts returned by :meth:`GradGuard.apply`
OK, SKIP = "ok", "skip"


def policy_from_env() -> str:
    """Resolve ``HOROVOD_GRAD_GUARD``; unknown values fail loudly (a typo
    silently disabling the guard would defeat its purpose)."""
    policy = os.environ.get(ENV_POLICY, "off").strip().lower() or "off"
    if policy not in POLICIES:
        raise ValueError(
            f"{ENV_POLICY}={policy!r} is not a valid policy; expected one "
            f"of {POLICIES}")
    return policy


def decode_rank_mask(mask: int, world: int) -> List[str]:
    """Human-readable rank list from an agreement bitmask. Bit 31 is the
    shared overflow bit for ranks >= 31 (int32 flag vector)."""
    ranks: List[str] = [str(r) for r in range(min(world, 31))
                        if mask & (1 << r)]
    if mask & (1 << 31) or (world > 31 and mask < 0):
        ranks.append(">=31")
    return ranks


def _rank_bit(rank: int) -> np.int32:
    # ranks past 30 share the sign bit; the verdict stays correct, only
    # the offender attribution coarsens
    return np.int32(1) << np.int32(min(rank, 31))


class GradGuard:
    """Per-rank guard instance; ``policy=None`` re-reads the env knob on
    every :meth:`apply` so tests can monkeypatch it per scenario."""

    def __init__(self, policy: "str | None" = None, prefix: str = "grad"):
        if policy is not None and policy not in POLICIES:
            raise ValueError(f"invalid GradGuard policy {policy!r}; "
                             f"expected one of {POLICIES}")
        self._policy = policy
        self._prefix = prefix
        self._step = 0

    def _resolve_policy(self) -> str:
        return self._policy if self._policy is not None else policy_from_env()

    # ------------------------------------------------------------------ apply
    def apply(self, grads, prefix: "str | None" = None) -> Tuple[str, "object"]:
        """Inspect a gradient pytree before it enters the allreduce.

        Returns ``(verdict, grads)``: verdict ``"skip"`` means the caller
        must drop the optimizer step globally (all ranks agree); ``"ok"``
        means proceed with the (possibly leaf-zeroed) gradients. Raises
        :class:`NonFiniteError` under the ``abort`` policy. With policy
        ``off`` this is a no-op returning the input untouched.
        """
        policy = self._resolve_policy()
        if policy == "off":
            return OK, grads
        import jax
        import jax.numpy as jnp

        self._step += 1
        prefix = prefix if prefix is not None else self._prefix
        pairs, treedef = jax.tree_util.tree_flatten_with_path(grads)
        if not pairs:
            return OK, grads
        paths, leaves = zip(*pairs)
        leaves = list(leaves)

        # chaos harness: nan@grad poisons this rank's first inexact leaf
        rank = basics.rank()
        inj = faultinject.shared_for_rank(rank)
        if inj is not None:
            for kind, _ in inj.actions_for("grad"):
                if kind == "nan":
                    for i, leaf in enumerate(leaves):
                        if jnp.issubdtype(jnp.asarray(leaf).dtype,
                                          jnp.inexact):
                            leaves[i] = jnp.full_like(jnp.asarray(leaf),
                                                      jnp.nan)
                            break

        # local detect: one fused boolean per leaf, a single host sync
        checks = [jnp.logical_not(jnp.all(jnp.isfinite(jnp.asarray(l))))
                  if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)
                  else jnp.asarray(False) for l in leaves]
        bad_local = np.asarray(jnp.stack(checks))
        n_bad = int(bad_local.sum())
        if n_bad:
            instruments.grad_nonfinite().inc(n_bad)

        # cross-rank agreement: every rank contributes its rank bit per
        # offending leaf; the summed int32 vector is the global verdict
        # (every rank participates every guarded step — the flag exchange
        # IS the agreement, there is no fast path that desyncs it)
        if basics.size() > 1:
            from ..ops import collective_ops as ops

            contrib = np.where(bad_local, _rank_bit(rank),
                               np.int32(0)).astype(np.int32)
            mask = np.asarray(ops.allreduce(
                contrib, name=f"{prefix}.__gradguard__", op=basics.Sum))
        else:
            mask = np.where(bad_local, _rank_bit(rank),
                            np.int32(0)).astype(np.int32)
        poisoned = mask != 0
        if not poisoned.any():
            return OK, grads

        names = [prefix + jax.tree_util.keystr(p)
                 for p, hit in zip(paths, poisoned) if hit]
        combined = int(np.bitwise_or.reduce(mask[poisoned]))
        offenders = decode_rank_mask(combined, basics.size())
        detail = (f"non-finite gradients at step {self._step}: "
                  f"tensor(s) {names} from rank(s) {offenders}")
        from .. import blackbox
        blackbox.record(blackbox.K_VERDICT, "gradguard", detail)
        if policy == "abort":
            blackbox.dump(detail)
            raise NonFiniteError(
                f"{detail} (HOROVOD_GRAD_GUARD=abort; use skip/zero to "
                "continue training through transient NaN/Inf)")
        if policy == "skip":
            instruments.steps_skipped().inc()
            logger.warning("gradguard: skipping optimizer step — %s", detail)
            return SKIP, grads
        # zero: nullify only the offending leaves, apply the rest
        logger.warning("gradguard: zeroing offending tensor(s) — %s", detail)
        leaves = [jnp.zeros_like(jnp.asarray(l)) if hit else l
                  for l, hit in zip(leaves, poisoned)]
        return OK, jax.tree_util.tree_unflatten(treedef, leaves)


# ------------------------------------------------------- per-rank singletons
# In the in-process thread cluster each rank thread needs its own step
# counter and injector hits; keyed by thread rank, reset with the engine.
_guards: dict = {}
_guards_lock = threading.Lock()


def default_guard() -> GradGuard:
    rank = basics.rank() if basics.is_initialized() else 0
    with _guards_lock:
        g = _guards.get(rank)
        if g is None:
            g = _guards[rank] = GradGuard()
        return g


def _reset_guards() -> None:
    with _guards_lock:
        _guards.clear()
    faultinject.reset_shared()


basics.register_shutdown_hook(_reset_guards)


def precheck_entry(entry) -> None:
    """Enqueue-side fast-fail for raw collective calls: under the
    ``abort`` policy, a non-finite ALLREDUCE/ADASUM input raises
    :class:`NonFiniteError` on the producing rank *before* it can poison
    peers. Unlike the optimizer-path guard this is a local verdict (no
    agreement round) — peers that already submitted the name will hit the
    collective watchdog instead of hanging (docs/fault-tolerance.md).
    Costs nothing unless HOROVOD_GRAD_GUARD=abort."""
    if policy_from_env() != "abort":
        return
    from ..runtime.messages import RequestType

    if entry.request_type not in (RequestType.ALLREDUCE, RequestType.ADASUM):
        return
    arr = entry.array
    if not np.issubdtype(np.asarray(arr).dtype, np.inexact):
        return
    import jax.numpy as jnp

    if not bool(jnp.all(jnp.isfinite(arr))):
        detail = (f"non-finite values in tensor {entry.tensor_name!r} "
                  f"submitted by rank(s) [{entry.rank}] "
                  "(HOROVOD_GRAD_GUARD=abort)")
        from .. import blackbox
        blackbox.record(blackbox.K_VERDICT, "gradguard", detail,
                        rank=entry.rank)
        blackbox.dump(detail)
        raise NonFiniteError(
            f"non-finite values in tensor {entry.tensor_name!r} submitted "
            f"by rank {entry.rank} (HOROVOD_GRAD_GUARD=abort)")
