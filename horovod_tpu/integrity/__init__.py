"""Data-plane integrity guard (docs/fault-tolerance.md).

PR 4 hardened the *control plane* (reconnect, heartbeats, CRC frames);
this package guards the *data plane* — the gradients, parameters and
collectives the control plane faithfully schedules:

* :class:`GradGuard` — non-finite gradient detection with a cross-rank
  agreement bit and the ``HOROVOD_GRAD_GUARD=off|skip|zero|abort``
  policy, wired into ``optim/distributed.py`` / ``ops/collective_ops.py``.
* :class:`ConsistencyAuditor` — periodic cross-rank parameter digest
  comparison (``HOROVOD_CONSISTENCY_INTERVAL``) with
  ``HOROVOD_CONSISTENCY_POLICY=warn|heal|abort`` (heal re-broadcasts from
  the root through the existing broadcast path).
* the collective watchdog — ``HOROVOD_COLLECTIVE_TIMEOUT`` promotes the
  stall inspector's warning into an enforced
  :class:`~..exceptions.CollectiveTimeoutError` naming the tensor and the
  missing ranks, and feeds the elastic ``rank_lost`` path
  (`runtime/pycontroller.py` / `runtime/coordinator.py` — the watchdog
  lives in the controllers because only they see all ranks' submissions).

All three pillars are drivable from the fault harness: ``nan@grad``,
``desync@param`` and ``hang@collective`` in ``HOROVOD_FAULT_SPEC``.
"""

from __future__ import annotations

from .auditor import ConsistencyAuditor, param_digest
from .gradguard import (OK, SKIP, GradGuard, default_guard, precheck_entry)

__all__ = ["GradGuard", "ConsistencyAuditor", "param_digest",
           "default_guard", "precheck_entry", "OK", "SKIP"]
