"""Cross-rank parameter consistency auditing with self-heal.

Replicated data parallelism has one invariant the rest of the stack builds
on: every rank holds bitwise-identical parameters (arXiv:1802.05799 §3).
Elastic re-rendezvous, reconnect replay, error-feedback residuals and
plain numerical bugs can all silently break it, after which the job keeps
"training" while the replicas drift. The auditor makes the invariant
observable and repairable:

* Every ``HOROVOD_CONSISTENCY_INTERVAL`` steps each rank folds its
  parameter pytree into a compact digest — per leaf ``[crc32_lo,
  crc32_hi, minbits, maxbits]`` (int32) over the raw bytes, so the
  comparison is exact (no float tolerance games).
* Rank 0's digest is broadcast (bit-exact — no arithmetic on the wire)
  and compared locally; a second int32 bitmask allreduce turns the local
  mismatches into a global verdict naming the divergent leaves and ranks
  (the same agreement shape GradGuard uses).
* Policy ``HOROVOD_CONSISTENCY_POLICY``:
  * ``warn``  (default) — log the divergent tensors/ranks.
  * ``heal``  — re-broadcast the full parameter set from the root through
    the existing broadcast path and count it in
    ``hvd_integrity_heals_total``.
  * ``abort`` — raise :class:`~..exceptions.ParameterDesyncError`.

Fault hook: ``desync@param`` in ``HOROVOD_FAULT_SPEC`` perturbs this
rank's first leaf right before the digest, driving detect→heal end to
end from the chaos harness (one hit per audit).
"""

from __future__ import annotations

import logging
import os
import zlib
from typing import List

import numpy as np

from .. import basics, faultinject
from ..exceptions import ParameterDesyncError
from ..metrics import instruments
from .gradguard import _rank_bit, decode_rank_mask

logger = logging.getLogger("horovod_tpu")

ENV_INTERVAL = "HOROVOD_CONSISTENCY_INTERVAL"
ENV_POLICY = "HOROVOD_CONSISTENCY_POLICY"
POLICIES = ("warn", "heal", "abort")

#: int32 digest words per parameter leaf
_WORDS = 4


def policy_from_env() -> str:
    policy = os.environ.get(ENV_POLICY, "warn").strip().lower() or "warn"
    if policy not in POLICIES:
        raise ValueError(
            f"{ENV_POLICY}={policy!r} is not a valid policy; expected one "
            f"of {POLICIES}")
    return policy


def interval_from_env() -> int:
    raw = os.environ.get(ENV_INTERVAL, "").strip()
    if not raw:
        return 0
    try:
        interval = int(raw)
    except ValueError:
        raise ValueError(f"{ENV_INTERVAL}={raw!r} must be an integer "
                         "step count (0 disables auditing)")
    return max(0, interval)


def param_digest(params) -> np.ndarray:
    """Fold a parameter pytree into one int32 vector, ``_WORDS`` entries
    per leaf: the leaf bytes' CRC32 split into two uint16 halves plus the
    min/max values bitcast to int32 (float bit patterns compare exactly;
    non-float leaves contribute their raw int min/max). Computed host-side
    — audits run every N steps, not on the step path."""
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    out = np.zeros(_WORDS * len(leaves), dtype=np.int32)
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes())
        out[_WORDS * i] = crc & 0xFFFF
        out[_WORDS * i + 1] = (crc >> 16) & 0xFFFF
        if arr.size:
            lo, hi = arr.min(), arr.max()
            if arr.dtype.kind == "f":
                bits = np.array([lo, hi], dtype=np.float32).view(np.int32)
            else:
                bits = np.array([lo, hi]).astype(np.int64).view(np.int32)[::2]
            out[_WORDS * i + 2] = bits[0]
            out[_WORDS * i + 3] = bits[1]
    return out


class ConsistencyAuditor:
    """Periodic digest audit; construct with explicit knobs or leave them
    ``None`` to re-read the env on every call (testable via monkeypatch).

    Use :meth:`maybe_audit` from a training loop (or the
    :class:`~..callbacks.ConsistencyCheckCallback` wrapper); it returns
    the params unchanged on non-audit steps and the (possibly healed)
    params on audit steps."""

    def __init__(self, interval: "int | None" = None,
                 policy: "str | None" = None, root_rank: int = 0,
                 prefix: str = "param"):
        if policy is not None and policy not in POLICIES:
            raise ValueError(f"invalid consistency policy {policy!r}; "
                             f"expected one of {POLICIES}")
        self._interval = interval
        self._policy = policy
        self._root = root_rank
        self._prefix = prefix
        self._step = 0
        self._audits = 0

    def maybe_audit(self, params):
        self._step += 1
        interval = (self._interval if self._interval is not None
                    else interval_from_env())
        if interval <= 0 or basics.size() <= 1 or self._step % interval:
            return params
        return self.audit(params)

    def audit(self, params):
        """One forced audit round: digest → root broadcast → agreement →
        policy. Collective — every rank must call it at the same point."""
        import jax
        import jax.numpy as jnp

        from ..ops import collective_ops as ops

        self._audits += 1
        rank = basics.rank()

        # chaos harness: desync@param perturbs this rank's first leaf
        inj = faultinject.shared_for_rank(rank)
        if inj is not None:
            for kind, _ in inj.actions_for("param"):
                if kind == "desync":
                    leaves, treedef = jax.tree_util.tree_flatten(params)
                    if leaves:
                        leaves[0] = jnp.asarray(leaves[0]) + 1
                        params = jax.tree_util.tree_unflatten(treedef, leaves)
                        logger.warning(
                            "faultinject: rank %s desynced a parameter "
                            "leaf before audit %d", rank, self._audits)

        digest = param_digest(params)
        root_digest = np.asarray(ops.broadcast(
            digest, self._root, name=f"{self._prefix}.__audit__.digest"))
        mismatch = (digest.reshape(-1, _WORDS)
                    != root_digest.reshape(-1, _WORDS)).any(axis=1)
        contrib = np.where(mismatch, _rank_bit(rank),
                           np.int32(0)).astype(np.int32)
        mask = np.asarray(ops.allreduce(
            contrib, name=f"{self._prefix}.__audit__.flag", op=basics.Sum))
        divergent = mask != 0
        if not divergent.any():
            return params

        names = self._leaf_names(params)
        bad = [names[i] for i in np.flatnonzero(divergent)]
        combined = int(np.bitwise_or.reduce(mask[divergent]))
        offenders = decode_rank_mask(combined, basics.size())
        instruments.param_desync().inc(int(divergent.sum()))
        detail = (f"parameter desync at audit {self._audits} (step "
                  f"{self._step}): tensor(s) {bad} diverged from rank "
                  f"{self._root} on rank(s) {offenders}")
        from .. import blackbox
        blackbox.record(blackbox.K_VERDICT, "auditor", detail)
        policy = (self._policy if self._policy is not None
                  else policy_from_env())
        if policy == "abort":
            blackbox.dump(detail)
            raise ParameterDesyncError(
                f"{detail} (HOROVOD_CONSISTENCY_POLICY=abort; use heal to "
                "re-broadcast from the root instead)")
        if policy == "heal":
            from ..optim.broadcast import broadcast_parameters

            logger.warning("auditor: healing — re-broadcasting parameters "
                           "from rank %d (%s)", self._root, detail)
            params = broadcast_parameters(
                params, self._root, prefix=f"{self._prefix}.__heal__")
            instruments.integrity_heals().inc()
            return params
        logger.warning("auditor: %s (HOROVOD_CONSISTENCY_POLICY=warn; "
                       "replicas are NO LONGER equivalent)", detail)
        return params

    def _leaf_names(self, params) -> List[str]:
        import jax

        return [self._prefix + jax.tree_util.keystr(p) for p, _ in
                jax.tree_util.tree_flatten_with_path(params)[0]]
