"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp`` axis.

Beyond-reference extension (the reference is DP-only). Stages hold stacked
parameters ``[S, ...]`` sharded over the ``pp`` mesh axis (one stage per
device group); inside ``shard_map`` a ``lax.scan`` runs ``M + S - 1`` ticks,
each tick applying the local stage and handing activations to the next
stage with ``lax.ppermute``. Autodiff gives the backward pipeline for free:
the transpose of ``ppermute`` is the reverse ``ppermute``, so ``jax.grad``
through the forward schedule IS the reverse schedule, bubbles included.

The bubble fraction is the classic (S-1)/(M+S-1) — pick ``n_microbatches``
well above the stage count. Outputs match running the stages sequentially
to float tolerance (microbatch shape changes matmul blocking, so the last
ulp can drift), which the tests pin.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _mark_varying(x, axis: str):
    """Mark a replicated value as device-varying over ``axis`` (pcast on
    current jax, pvary on older releases where pcast doesn't exist yet)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis,), to="varying")
    return lax.pvary(x, (axis,))


def make_pp_mesh(pp: int, devices=None) -> Mesh:
    devices = list(devices) if devices is not None else list(jax.devices())
    if pp > len(devices):
        raise ValueError(f"pp={pp} exceeds {len(devices)} devices")
    return Mesh(np.asarray(devices[:pp]), ("pp",))


def stack_stage_params(init_fn: Callable, rng, n_stages: int, sample):
    """Init one param tree per stage (distinct rngs) and stack leading dim:
    ``init_fn(rng, sample) -> params``; result leaves are ``[S, ...]``."""
    trees = [init_fn(jax.random.fold_in(rng, s), sample)
             for s in range(n_stages)]
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *trees)


def shard_stage_params(stacked, mesh: Mesh, pp_axis: str = "pp"):
    """Place stacked stage params with the stage dim over ``pp``."""
    def one(leaf):
        return jax.device_put(
            leaf, NamedSharding(mesh, P(pp_axis)))

    return jax.tree_util.tree_map(one, stacked)


def make_pipeline_fn(stage_fn: Callable, mesh: Mesh, n_microbatches: int,
                     pp_axis: str = "pp") -> Callable:
    """Build ``f(stacked_params, x) -> y`` running the GPipe schedule.

    ``stage_fn(stage_params, activation) -> activation`` must preserve the
    activation shape (classic homogeneous-stage pipelining). ``x`` is the
    global batch ``[B, ...]`` with ``B % n_microbatches == 0``; the result
    is the composition of all ``S`` stages applied to every microbatch,
    replicated on every device.
    """
    S = mesh.shape[pp_axis]
    M = n_microbatches
    fwd = [(i, (i + 1) % S) for i in range(S)]

    def body(stacked, x):
        lead = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        if lead != 1:
            raise ValueError(
                f"stacked stage params have {lead * S} stages but the "
                f"mesh's {pp_axis} size is {S}; each device must hold "
                "exactly one stage")
        p = jax.tree_util.tree_map(lambda l: l[0], stacked)  # own stage
        idx = lax.axis_index(pp_axis)
        B = x.shape[0]
        if B % M:
            raise ValueError(
                f"batch size {B} is not divisible by "
                f"n_microbatches={M}")
        mb = B // M
        xs = x.reshape((M, mb) + x.shape[1:])

        def tick(carry, t):
            act = carry
            # stage 0 injects microbatch t (clamped; ticks >= M feed zeros
            # whose outputs are never collected)
            t_in = jnp.minimum(t, M - 1)
            inject = jnp.where(t < M,
                               lax.dynamic_index_in_dim(xs, t_in, 0,
                                                        keepdims=False),
                               jnp.zeros_like(xs[0]))
            inp = jnp.where(idx == 0, inject, act)
            out = stage_fn(p, inp)
            nxt = lax.ppermute(out, pp_axis, fwd)
            return nxt, out

        # initial carry must be device-varying like the ppermute output,
        # or the scan carry types disagree under shard_map's vma tracking
        carry0 = _mark_varying(jnp.zeros_like(xs[0]), pp_axis)
        _, outs = lax.scan(tick, carry0, jnp.arange(M + S - 1))
        # the LAST stage's outputs at ticks S-1 .. S-1+M-1 are microbatches
        # 0..M-1; everyone else contributes zeros to the psum-broadcast
        ys = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
        ys = jnp.where(idx == S - 1, ys, jnp.zeros_like(ys))
        ys = lax.psum(ys, pp_axis)
        return ys.reshape((B,) + ys.shape[2:])

    fn = jax.shard_map(body, mesh=mesh, in_specs=(P(pp_axis), P()),
                       out_specs=P())
    return jax.jit(fn)


def make_pp_train_step(stage_fn: Callable, loss_head: Callable, tx,
                       mesh: Mesh, n_microbatches: int,
                       pp_axis: str = "pp") -> Callable:
    """Jitted pipeline training step.

    ``loss_head(final_activations, targets) -> scalar``. Returns
    ``step(stacked_params, opt_state, x, targets) -> (params, opt, loss)``
    — gradients flow back through the reverse pipeline automatically.
    """
    import optax

    pipe = make_pipeline_fn(stage_fn, mesh, n_microbatches, pp_axis)

    def loss_fn(params, x, targets):
        return loss_head(pipe(params, x), targets)

    def step(params, opt_state, x, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step)
