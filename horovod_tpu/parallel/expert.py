"""Expert parallelism: a switch-style MoE layer sharded over an ``ep`` axis.

Beyond-reference extension (the reference is DP-only). The MoE MLP holds
all experts as stacked parameter tensors ``[E, d, hidden]`` / ``[E,
hidden, d]``; sharding the expert dimension over the mesh's ``ep`` axis
puts ``E/ep`` experts on each device group.

Two dispatch strategies (docs/moe.md):

* **exact** (default, the numerical reference): dense one-hot
  dispatch/combine einsums over the full token set — every token reaches
  its routed expert, the communication is inserted by GSPMD, and the
  sharded computation is numerically identical to the unsharded one
  (which the tests pin). O(E·N·d) compute.
* **capacity** (the classic Switch recipe, ``dispatch="capacity"``):
  fixed-size per-expert buffers (``capacity = ceil(CF · N / E)``),
  position-in-expert via cumsum, tokens past capacity dropped (they
  contribute zero to the MoE output and are counted), and the token
  exchange is an explicit ``all_to_all`` over the ``ep`` axis inside a
  ``shard_map`` — which is where the quantized wire engages:
  ``HOROVOD_MOE_WIRE=int8|int4`` ships the exchange through the fused
  quantize+pack kernels (``ops/pallas_kernels``, the same
  ``[payload | 4 f32-scale bytes]`` rows and eligibility fallbacks as
  ``spmd.py``'s quantized ring) with an EF-SGD residual banked per
  exchange direction. Router logits, gates, and gradients always stay on
  the exact wire.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import spmd
from .tensor import make_2d_mesh, make_sharded_train_step


class MoEMLP(nn.Module):
    """Top-1 (switch) routed MLP with a load-balancing auxiliary loss.

    Returns ``(y, aux_loss)``; add ``aux_weight * aux_loss`` to the
    training loss (Switch Transformer's balance loss: E * sum_e f_e * p_e,
    with f the fraction of tokens routed to e and p the mean router
    probability).
    """

    num_experts: int
    hidden_mult: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        b, t, d = x.shape
        e, h = self.num_experts, self.hidden_mult * x.shape[-1]
        x2 = x.reshape(b * t, d)

        router = nn.Dense(e, dtype=jnp.float32, param_dtype=jnp.float32,
                          name="router")(x2.astype(jnp.float32))
        probs = jax.nn.softmax(router, axis=-1)          # [N, E]
        expert_idx = jnp.argmax(probs, axis=-1)          # [N]
        onehot = jax.nn.one_hot(expert_idx, e, dtype=probs.dtype)
        gate = (probs * onehot).sum(-1)                  # chosen prob

        w_in = self.param("w_in", nn.initializers.normal(0.02), (e, d, h),
                          jnp.float32)
        w_out = self.param("w_out", nn.initializers.normal(0.02), (e, h, d),
                           jnp.float32)
        # dispatch/combine as einsums over the (shardable) expert dim:
        # every expert sees the full token set masked by its assignment
        xe = jnp.einsum("nd,ne->end", x2.astype(self.dtype),
                        onehot.astype(self.dtype))       # [E, N, d]
        he = nn.gelu(jnp.einsum("end,edh->enh", xe,
                                w_in.astype(self.dtype)))
        ye = jnp.einsum("enh,ehd->end", he, w_out.astype(self.dtype))
        y = ye.sum(0) * gate[:, None].astype(self.dtype)  # combine
        frac = onehot.mean(0)                            # f_e
        balance = e * jnp.sum(frac * probs.mean(0))      # aux loss
        return y.reshape(b, t, d).astype(x.dtype), balance.astype(jnp.float32)


# ------------------------------------------------------------ knobs & math
_MOE_WIRES = ("int8", "int4")


def moe_wire(value: Optional[str] = None) -> str:
    """Resolve the MoE token-exchange wire mode (``HOROVOD_MOE_WIRE``).

    Returns ``""`` (wire off — the exact bf16/f32 all_to_all), ``"int8"``
    or ``"int4"``. ``value`` overrides the env var (the
    ``make_ep_train_step(wire=...)`` argument). int4 must pass the PR 10
    ``ConvergenceGate`` A/B harness; a refusal downgrades to int8 — the
    same admission rule as ``HOROVOD_GSPMD_WIRE``
    (`ops/adaptive.admit_wire`).
    """
    v = os.environ.get("HOROVOD_MOE_WIRE", "") if value is None else value
    v = (v or "").strip().lower()
    if v in ("", "0", "off", "none"):
        return ""
    if v not in _MOE_WIRES:
        raise ValueError(f"HOROVOD_MOE_WIRE must be int8|int4|off, got {v!r}")
    from ..ops.adaptive import admit_wire

    return admit_wire(v)


def expert_capacity(num_tokens: int, num_experts: int,
                    capacity_factor: float) -> int:
    """Per-expert buffer slots for ``num_tokens`` routed tokens:
    ``ceil(CF · N / E)``, at least 1 (the Switch Transformer rule). At
    CF=1.0 a perfectly balanced router drops nothing; CF=1.25 (the paper
    default) leaves 25% headroom for imbalance."""
    if num_tokens <= 0 or num_experts <= 0:
        raise ValueError(
            f"need positive tokens/experts, got {num_tokens}/{num_experts}")
    if capacity_factor <= 0:
        raise ValueError(f"capacity_factor must be positive, "
                         f"got {capacity_factor}")
    return max(1, int(math.ceil(capacity_factor * num_tokens / num_experts)))


def init_moe_params(key, d: int, num_experts: int, hidden_mult: int = 4):
    """Functional (non-flax) parameter tree for the capacity-dispatch MoE:
    ``router`` (replicated f32) plus the expert-stacked ``w_in``/``w_out``
    — the same names :func:`ep_param_spec` shards. Init matches
    :class:`MoEMLP` (normal 0.02, zero router bias)."""
    h = hidden_mult * d
    kr, ki, ko = jax.random.split(key, 3)
    return {
        "router": {
            "kernel": 0.02 * jax.random.normal(kr, (d, num_experts),
                                               jnp.float32),
            "bias": jnp.zeros((num_experts,), jnp.float32),
        },
        "w_in": 0.02 * jax.random.normal(ki, (num_experts, d, h),
                                         jnp.float32),
        "w_out": 0.02 * jax.random.normal(ko, (num_experts, h, d),
                                          jnp.float32),
    }


def _router(params, x2):
    """Shared exact top-1 routing: f32 logits -> (probs, onehot, gate).
    The router ALWAYS computes and exchanges exactly — quantizing routing
    decisions desynchronizes dispatch across ranks (docs/moe.md)."""
    logits = (x2.astype(jnp.float32) @ params["router"]["kernel"]
              + params["router"]["bias"])
    probs = jax.nn.softmax(logits, axis=-1)              # [N, E]
    onehot = jax.nn.one_hot(jnp.argmax(probs, axis=-1), probs.shape[-1],
                            dtype=jnp.float32)
    gate = (probs * onehot).sum(-1)                      # chosen prob
    return probs, onehot, gate


def dense_moe_apply(params, x2) -> Tuple[jax.Array, jax.Array]:
    """Exact dense one-hot dispatch on a functional param tree (the
    numerical reference the capacity path is measured against): ``x2``
    is ``[N, d]``; returns ``(y [N, d], balance aux loss)``. Same math
    as :class:`MoEMLP` in f32."""
    e = params["w_in"].shape[0]
    probs, onehot, gate = _router(params, x2)
    xe = jnp.einsum("nd,ne->end", x2.astype(jnp.float32), onehot)
    he = jax.nn.gelu(jnp.einsum("end,edh->enh", xe, params["w_in"]))
    ye = jnp.einsum("enh,ehd->end", he, params["w_out"])
    y = ye.sum(0) * gate[:, None]
    balance = e * jnp.sum(onehot.mean(0) * probs.mean(0))
    return y.astype(x2.dtype), balance.astype(jnp.float32)


def dispatch_mask(onehot, capacity: int):
    """Switch position-in-expert assignment: ``onehot`` is the ``[N, E]``
    top-1 routing; returns ``(dmask [N, E, C], keep [N])`` where
    ``dmask[n, e, c] = 1`` iff token n is the c-th token routed to expert
    e with ``c < capacity``. Position comes from a cumulative sum over
    the token dimension, so earlier tokens win slots and overflow tokens
    get an all-zero row (dropped — they contribute nothing to the
    dispatch einsum and recombine to zero)."""
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot    # [N, E]
    pos_tok = pos.sum(-1)                                # rank within expert
    keep = pos_tok < capacity
    slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                          dtype=jnp.float32)             # 0-rows past C
    dmask = onehot[:, :, None] * slot[:, None, :]
    return dmask, keep


class SwitchDispatch:
    """Capacity-factor Switch dispatch bound to one train-step invocation.

    Built by the capacity train step and handed to ``loss_fn(params,
    batch, moe)``; call ``moe(moe_params, x2)`` with the functional param
    tree (:func:`init_moe_params` layout; expert leaves arrive ep-local
    inside the step's shard_map) and the local ``[n_loc, d]`` token slab.
    Returns ``(y, aux_loss)`` like :func:`dense_moe_apply`.

    The first call banks dispatch statistics (per-expert load, dropped
    tokens — psum'd, so identical on every device) and the new EF
    residual pair on the object; the step returns them through
    ``has_aux`` so nothing leaks out of the gradient trace. Later calls
    (multi-layer MoE) exchange with zero EF — only the first exchange
    pair carries the banked residual.
    """

    def __init__(self, dp_axis: str, ep_axis: str, capacity_factor: float,
                 wire: str, block: Optional[int], ef_loc):
        self.dp_axis = dp_axis
        self.ep_axis = ep_axis
        self.capacity_factor = capacity_factor
        self.wire = wire
        self.block = block
        self._ef_loc = ef_loc          # [2, E, C, d] this device's rows
        self.stats = None              # banked by the first __call__
        self.new_ef = None

    def __call__(self, params, x2) -> Tuple[jax.Array, jax.Array]:
        axes = (self.dp_axis, self.ep_axis)
        ep = jax.lax.psum(1, self.ep_axis)
        e_loc = params["w_in"].shape[0]                  # ep-local experts
        e = ep * e_loc
        n_loc, d = x2.shape
        cap = expert_capacity(n_loc, e, self.capacity_factor)

        probs, onehot, gate = _router(params, x2)
        dmask, keep = dispatch_mask(onehot, cap)
        buf = jnp.einsum("nec,nd->ecd", dmask,
                         x2.astype(jnp.float32))         # [E, C, d]

        first = self.stats is None
        ef = self._ef_loc if (first and self._ef_loc is not None) else None
        if ef is not None and ef.shape[1:] != buf.shape:
            raise ValueError(
                f"EF residual shaped {ef.shape[1:]} does not match the "
                f"[E, C, d] exchange {buf.shape}; rebuild the optimizer "
                f"state with moe_opt_state() for this batch size")

        def exchange(z, direction):
            if not self.wire:
                y = jax.lax.all_to_all(z, self.ep_axis, 0, 0, tiled=True)
                return y, (jnp.zeros_like(z) if ef is not None else None)
            out = spmd.quantized_all_to_all(
                z, self.ep_axis, self.wire, self.block,
                ef=ef[direction] if ef is not None else None)
            return out if ef is not None else (out, None)

        # dispatch: peer p owns global experts [p*e_loc, (p+1)*e_loc) —
        # buf's expert-major dim 0 is already grouped by destination peer
        recv, ef_d = exchange(buf, 0)
        xe = (recv.reshape(ep, e_loc, cap, d)
              .transpose(1, 0, 2, 3).reshape(e_loc, ep * cap, d))
        he = jax.nn.gelu(jnp.einsum("egd,edh->egh", xe, params["w_in"]))
        ye = jnp.einsum("egh,ehd->egd", he, params["w_out"])
        back = (ye.reshape(e_loc, ep, cap, d)
                .transpose(1, 0, 2, 3).reshape(e, cap, d))
        # combine: group p of `back` holds our experts' outputs for the
        # tokens peer p sent; the reverse exchange returns every token's
        # expert output to its home device
        out, ef_c = exchange(back, 1)
        y = jnp.einsum("ecd,nec->nd", out, dmask) * gate[:, None]

        # balance loss over the GLOBAL batch (pmean of local means)
        frac = jax.lax.pmean(onehot.mean(0), axes)
        pmean_probs = jax.lax.pmean(probs.mean(0), axes)
        balance = e * jnp.sum(frac * pmean_probs)

        if first:
            load = jax.lax.psum(onehot.sum(0), axes)     # [E] tokens/expert
            dropped = jax.lax.psum(
                n_loc - keep.astype(jnp.float32).sum(), axes)
            self.stats = {"load": load, "dropped": dropped,
                          "capacity": jnp.asarray(cap, jnp.float32)}
            if self._ef_loc is not None:
                self.new_ef = jnp.stack([ef_d, ef_c])
        return y.astype(x2.dtype), balance.astype(jnp.float32)


# ------------------------------------------------------- sharding helpers
def make_dp_ep_mesh(dp: int, ep: int, devices=None) -> Mesh:
    return make_2d_mesh(("dp", "ep"), (dp, ep), devices)


def _path_name(entry) -> str:
    """One jax.tree_util path entry as its plain key/attr name — DictKey,
    GetAttrKey, and SequenceKey all stringify to the bare name instead of
    repr noise like ``['w_in']``."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def ep_param_spec(path_keys, leaf, ep_axis: str = "ep") -> P:
    """Stacked expert tensors shard dim 0 (the expert dim) over ``ep``;
    the router and everything else replicate."""
    names = [str(k) for k in path_keys]
    if names and names[-1] in ("w_in", "w_out"):
        return P(ep_axis)
    return P()


def ep_specs(tree, ep_axis: str = "ep"):
    """Pytree of PartitionSpecs matching :func:`ep_param_spec` — shared by
    param placement, optimizer-state placement, and the capacity step's
    shard_map in/out specs (optax state mirrors the param tree, so its
    expert leaves keep the ``w_in``/``w_out`` path suffix)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: ep_param_spec(
            [_path_name(p) for p in path], leaf, ep_axis), tree)


def shard_params_ep(params, mesh: Mesh, ep_axis: str = "ep"):
    ep = mesh.shape[ep_axis]

    def one(path, leaf):
        names = [_path_name(p) for p in path]
        spec = ep_param_spec(names, leaf, ep_axis)
        if spec and spec[0] == ep_axis and leaf.shape[0] % ep != 0:
            raise ValueError(
                f"{'/'.join(names)}: expert dim "
                f"{leaf.shape[0]} not divisible by ep={ep}")
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(one, params)


def moe_opt_state(tx, params, mesh: Mesh, num_tokens: int,
                  capacity_factor: float = 1.25, dp_axis: str = "dp",
                  ep_axis: str = "ep"):
    """Initial ``(inner_state, ef_residual)`` for a capacity-dispatch step.

    ``num_tokens`` is the GLOBAL tokens per step (batch × seq); the EF
    residual covers both exchange directions as one zero-initialized leaf
    of global shape ``[n_devices, 2, E, C, d]`` sharded one row per
    device over ``(dp, ep)`` — inside the step's shard_map each device
    sees exactly its own ``[2, E, C, d]`` rows, mirroring
    :func:`spmd.quantized_opt_state`. The inner optimizer state is placed
    with the same ep sharding as the params (optax state mirrors the
    param tree)."""
    dp, ep = mesh.shape[dp_axis], mesh.shape[ep_axis]
    world = dp * ep
    if num_tokens % world:
        raise ValueError(f"global tokens {num_tokens} not divisible by "
                         f"{world} devices")
    e, d, _ = params["w_in"].shape
    cap = expert_capacity(num_tokens // world, e, capacity_factor)
    ef = jax.device_put(
        jnp.zeros((world, 2, e, cap, d), jnp.float32),
        NamedSharding(mesh, P((dp_axis, ep_axis))))
    inner = shard_params_ep(tx.init(params), mesh, ep_axis)
    return inner, ef


# ------------------------------------------------------------- train steps
def make_ep_train_step(loss_fn: Callable, tx, mesh: Mesh,
                       dp_axis: str = "dp", ep_axis: str = "ep",
                       dispatch: str = "exact",
                       capacity_factor: float = 1.25,
                       wire: Optional[str] = None,
                       block: Optional[int] = None,
                       donate: bool = True) -> Callable:
    """EP train step.

    ``dispatch="exact"`` (the default): expert params stay ep-sharded,
    batch over ``dp``, dense one-hot dispatch with GSPMD-inserted
    communication (see :func:`tensor.make_sharded_train_step`) — with the
    knobs unset this compiles the exact same program as before the
    capacity variant existed (the pin tested in tests/test_moe.py).

    ``dispatch="capacity"``: the Switch recipe. ``loss_fn(params, batch,
    moe) -> scalar`` receives a :class:`SwitchDispatch` and the LOCAL
    batch shard; the step runs as a shard_map over the full ``(dp, ep)``
    mesh with per-device gradients reduced explicitly (pmean over both
    axes for replicated leaves; the backward all_to_all already sums the
    ep group for expert shards, so those psum over ``dp`` only). ``wire``
    resolves ``HOROVOD_MOE_WIRE`` at build time (:func:`moe_wire`,
    including the int4 gate admission); opt state must come from
    :func:`moe_opt_state`. Returns ``step(params, opt_state, batch) ->
    (params, opt_state, loss, stats)`` with ``stats`` the banked
    dispatch statistics; byte/load/drop accounting ticks eagerly per call
    (``step.jitted`` is the bare compiled step).
    """
    if dispatch == "exact":
        return make_sharded_train_step(loss_fn, tx, mesh, batch_axis=dp_axis)
    if dispatch != "capacity":
        raise ValueError(f"dispatch must be exact|capacity, got {dispatch!r}")
    import optax

    wire = moe_wire(wire)
    block = spmd._wire_block(block)
    dp, ep = mesh.shape[dp_axis], mesh.shape[ep_axis]
    world = dp * ep
    axes = (dp_axis, ep_axis)

    def local_step(params, inner, ef, batch):
        def local_loss(p):
            moe = SwitchDispatch(dp_axis, ep_axis, capacity_factor, wire,
                                 block, ef[0])
            loss = loss_fn(p, batch, moe)
            if moe.stats is None:
                raise ValueError(
                    "dispatch='capacity' requires loss_fn(params, batch, "
                    "moe) to call moe(moe_params, tokens)")
            return loss, (moe.stats, moe.new_ef)

        (loss, (stats, new_ef)), grads = jax.value_and_grad(
            local_loss, has_aux=True)(params)
        specs = ep_specs(grads, ep_axis)

        def reduce_one(spec, g):
            # replicated leaves: mean of per-device grads over the whole
            # mesh. ep-sharded leaves: each device's grad already sums its
            # ep row's cotangents (the backward all_to_all delivered
            # them), so only the dp copies remain to fold in — psum over
            # dp, then the same 1/world of the global-mean loss.
            if spec and spec[0] == ep_axis:
                return jax.lax.psum(g, dp_axis) / world
            return jax.lax.pmean(g, axes)

        grads = jax.tree_util.tree_map(reduce_one, specs, grads)
        updates, inner = tx.update(grads, inner, params)
        params = optax.apply_updates(params, updates)
        loss = jax.lax.pmean(loss, axes)
        return params, inner, new_ef[None], loss, stats

    def step(params, opt_state, batch):
        inner, ef = opt_state
        p_specs = ep_specs(params, ep_axis)
        i_specs = ep_specs(inner, ep_axis)
        fn = spmd._shard_map(
            local_step, mesh,
            in_specs=(p_specs, i_specs, P(axes), P(axes)),
            out_specs=(p_specs, i_specs, P(axes), P(), P()))
        params, inner, ef, loss, stats = fn(params, inner, ef, batch)
        return params, (inner, ef), loss, stats

    jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())

    @functools.wraps(jitted)
    def instrumented(params, opt_state, batch):
        per_peer = int(np.prod(opt_state[1].shape[2:])) // ep  # E_loc·C·d
        out = jitted(params, opt_state, batch)
        _record_moe(out[3], capacity_factor, wire, per_peer, ep, block)
        return out

    instrumented.jitted = jitted  # .lower()/.compile() escape hatch
    return instrumented


def _record_moe(stats, capacity_factor: float, wire: str, per_peer: int,
                ep: int, block: int):
    """Truthful eager accounting for one capacity-dispatch step (counters
    cannot tick inside the compiled program): per-expert load and
    imbalance gauges, the dropped-token counter, and — when the wire is
    on — exchange bytes from the same catalog the bench reads
    (`ops/compression.moe_wire_footprint`)."""
    from ..metrics import instruments
    from ..ops import compression as comp

    load = np.asarray(stats["load"], dtype=np.float64)
    for i, v in enumerate(load):
        instruments.expert_load().labels(expert=str(i)).set(float(v))
    mean = float(load.mean()) if load.size else 0.0
    instruments.moe_load_imbalance().set(
        float(load.max()) / mean if mean > 0 else 0.0)
    instruments.moe_dropped_tokens().inc(float(stats["dropped"]))
    instruments.moe_capacity_factor().set(float(capacity_factor))
    if wire and spmd._wire_eligible(per_peer, jnp.float32, wire, block):
        wire_b = comp.moe_wire_footprint(per_peer, wire, ep, block)
        exact_b = comp.moe_wire_footprint(per_peer, "none", ep, block)
        instruments.wire_bytes().labels(
            compression=f"moe-{wire}").inc(wire_b)
        instruments.wire_bytes_exact().inc(exact_b)
