"""Expert parallelism: a switch-style MoE layer sharded over an ``ep`` axis.

Beyond-reference extension (the reference is DP-only). The MoE MLP holds
all experts as stacked parameter tensors ``[E, d, hidden]`` / ``[E,
hidden, d]``; sharding the expert dimension over the mesh's ``ep`` axis
puts ``E/ep`` experts on each device group, and the one-hot dispatch /
combine einsums become the token-exchange communication — inserted by
GSPMD, the compiler-native analogue of hand-written MoE all_to_alls.

Dispatch is exact (dense one-hot, no capacity drops): every token reaches
its routed expert, so the sharded computation is numerically identical to
the unsharded one — which the tests pin. A capacity-factor variant (drop +
all_to_all over fixed-size buffers, the classic Switch recipe) trades that
exactness for bounded memory; exactness is the right default at test scale.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .tensor import make_2d_mesh, make_sharded_train_step


class MoEMLP(nn.Module):
    """Top-1 (switch) routed MLP with a load-balancing auxiliary loss.

    Returns ``(y, aux_loss)``; add ``aux_weight * aux_loss`` to the
    training loss (Switch Transformer's balance loss: E * sum_e f_e * p_e,
    with f the fraction of tokens routed to e and p the mean router
    probability).
    """

    num_experts: int
    hidden_mult: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        b, t, d = x.shape
        e, h = self.num_experts, self.hidden_mult * x.shape[-1]
        x2 = x.reshape(b * t, d)

        router = nn.Dense(e, dtype=jnp.float32, param_dtype=jnp.float32,
                          name="router")(x2.astype(jnp.float32))
        probs = jax.nn.softmax(router, axis=-1)          # [N, E]
        expert_idx = jnp.argmax(probs, axis=-1)          # [N]
        onehot = jax.nn.one_hot(expert_idx, e, dtype=probs.dtype)
        gate = (probs * onehot).sum(-1)                  # chosen prob

        w_in = self.param("w_in", nn.initializers.normal(0.02), (e, d, h),
                          jnp.float32)
        w_out = self.param("w_out", nn.initializers.normal(0.02), (e, h, d),
                           jnp.float32)
        # dispatch/combine as einsums over the (shardable) expert dim:
        # every expert sees the full token set masked by its assignment
        xe = jnp.einsum("nd,ne->end", x2.astype(self.dtype),
                        onehot.astype(self.dtype))       # [E, N, d]
        he = nn.gelu(jnp.einsum("end,edh->enh", xe,
                                w_in.astype(self.dtype)))
        ye = jnp.einsum("enh,ehd->end", he, w_out.astype(self.dtype))
        y = ye.sum(0) * gate[:, None].astype(self.dtype)  # combine

        frac = onehot.mean(0)                            # f_e
        balance = e * jnp.sum(frac * probs.mean(0))      # aux loss
        return y.reshape(b, t, d).astype(x.dtype), balance.astype(jnp.float32)


def make_dp_ep_mesh(dp: int, ep: int, devices=None) -> Mesh:
    return make_2d_mesh(("dp", "ep"), (dp, ep), devices)


def ep_param_spec(path_keys, leaf, ep_axis: str = "ep") -> P:
    """Stacked expert tensors shard dim 0 (the expert dim) over ``ep``;
    the router and everything else replicate."""
    names = [str(k) for k in path_keys]
    if names and names[-1] in ("w_in", "w_out"):
        return P(ep_axis)
    return P()


def shard_params_ep(params, mesh: Mesh, ep_axis: str = "ep"):
    ep = mesh.shape[ep_axis]

    def one(path, leaf):
        spec = ep_param_spec(
            [p.key if hasattr(p, "key") else p.name for p in path], leaf,
            ep_axis)
        if spec and spec[0] == ep_axis and leaf.shape[0] % ep != 0:
            raise ValueError(
                f"{'/'.join(str(p) for p in path)}: expert dim "
                f"{leaf.shape[0]} not divisible by ep={ep}")
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(one, params)


def make_ep_train_step(loss_fn: Callable, tx, mesh: Mesh,
                       dp_axis: str = "dp") -> Callable:
    """EP train step: expert params stay ep-sharded, batch over ``dp``
    (see :func:`tensor.make_sharded_train_step`)."""
    return make_sharded_train_step(loss_fn, tx, mesh, batch_axis=dp_axis)
