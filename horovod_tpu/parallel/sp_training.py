"""Sequence-parallel (+ data-parallel) LM training over a (dp, sp) mesh.

No reference counterpart — Horovod 0.18.2 is data-parallel only (SURVEY §5
"Long-context: absent") — this is the framework's first-class long-context
training path. Composition:

  * mesh ``(dp, sp)``: batch sharded over ``dp``, sequence sharded over
    ``sp``; params and optimizer state replicated.
  * the model's attention is ring attention over ``sp``
    (`ring_attention.py`): K/V blocks rotate the ring via ``lax.ppermute``
    (ICI neighbor hops) while each hop's block compute runs the Pallas flash
    kernel; activations per chip stay O(T/sp).
  * backward: AD of ``ppermute`` is the reverse ring — XLA schedules the
    reverse hops exactly like the forward ones. Parameter gradients are the
    ``pmean`` over BOTH axes of each shard's local-loss gradient — with
    equal-size shards this equals the gradient of the global mean loss, the
    same invariant as the reference's DP gradient averaging
    (`tensorflow/__init__.py:117`), extended to the sequence axis.

Usage::

    mesh  = make_dp_sp_mesh(dp=2, sp=4)
    model = sp_model(TransformerLMTiny, vocab_size=V)   # ring attention
    step  = make_sp_train_step(model, optax.adamw(3e-4), mesh)
    params, opt_state, loss = step(params, opt_state, tokens, targets)
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ring_attention import ring_attention

DP_AXIS = "dp"
SP_AXIS = "sp"


def make_dp_sp_mesh(dp: int, sp: int, devices=None) -> Mesh:
    """(dp, sp) mesh over the first dp*sp devices. On real hardware, lay sp
    along the ICI ring (ring attention hops are neighbor transfers)."""
    devices = list(jax.devices() if devices is None else devices)[:dp * sp]
    if len(devices) < dp * sp:
        raise ValueError(f"need {dp * sp} devices, have {len(devices)}")
    return Mesh(np.asarray(devices).reshape(dp, sp), (DP_AXIS, SP_AXIS))


def sp_model(model_cls, sp_axis: str = SP_AXIS, **kwargs):
    """Instantiate a model class (e.g. ``TransformerLM``) with ring attention
    over ``sp_axis`` as its attention function."""
    attn = partial(ring_attention, axis_name=sp_axis, causal=True)
    return model_cls(attn_fn=attn, **kwargs)


def _check_global_seq_len(model, t_local: int, mesh: Mesh, sp_axis: str):
    """Inside shard_map the model only sees the LOCAL block, so its own
    bounds check can't catch a GLOBAL sequence longer than max_seq_len
    (pos_offset is traced). The global length sp * t_local is static here —
    enforce it at trace time so over-length SP runs fail loudly instead of
    jnp.take silently clipping position embeddings."""
    max_len = getattr(model, "max_seq_len", None)
    if max_len is not None:
        t_global = mesh.shape[sp_axis] * t_local
        if t_global > max_len:
            raise ValueError(
                f"global sequence length {t_global} "
                f"({mesh.shape[sp_axis]} sp shards x {t_local}) exceeds "
                f"model max_seq_len={max_len}")


def make_sp_train_step(model, tx, mesh: Mesh, dp_axis: str = DP_AXIS,
                       sp_axis: str = SP_AXIS, manual_axes=None):
    """Jitted full training step: ``(params, opt_state, tokens, targets) ->
    (params, opt_state, loss)``.

    ``tokens``/``targets`` are GLOBAL ``[B, T]`` int arrays (shift-by-one
    target construction happens before sharding, so next-token targets are
    correct across shard boundaries); the step shards them ``P(dp, sp)``.

    ``manual_axes`` restricts which mesh axes shard_map makes manual
    (default: all). `parallel/hybrid.py` passes {dp, sp} so a third ``tp``
    axis stays GSPMD-automatic and tensor-parallel param shardings flow
    through this same step unchanged.
    """
    import optax

    def local_step(params, opt_state, tokens, targets):
        t_local = tokens.shape[1]
        _check_global_seq_len(model, t_local, mesh, sp_axis)
        off = lax.axis_index(sp_axis) * t_local

        def loss_fn(p):
            logits = model.apply({"params": p}, tokens, pos_offset=off)
            from ..models.transformer import lm_loss

            return lm_loss(logits, targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = lax.pmean(grads, (dp_axis, sp_axis))
        loss = lax.pmean(loss, (dp_axis, sp_axis))
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss

    data_spec = P(dp_axis, sp_axis)
    extra = {} if manual_axes is None else {"axis_names": set(manual_axes)}
    fn = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(), data_spec, data_spec),
        out_specs=(P(), P(), P()),
        check_vma=False, **extra)
    return jax.jit(fn, donate_argnums=(0, 1))


def make_sp_forward(model, mesh: Mesh, dp_axis: str = DP_AXIS,
                    sp_axis: str = SP_AXIS):
    """Jitted sequence-parallel forward: global [B, T] tokens -> logits."""

    def local_fwd(params, tokens):
        _check_global_seq_len(model, tokens.shape[1], mesh, sp_axis)
        off = lax.axis_index(sp_axis) * tokens.shape[1]
        return model.apply({"params": params}, tokens, pos_offset=off)

    data_spec = P(dp_axis, sp_axis)
    fn = jax.shard_map(local_fwd, mesh=mesh,
                       in_specs=(P(), data_spec),
                       out_specs=P(dp_axis, sp_axis), check_vma=False)
    return jax.jit(fn)


def replicate_to_mesh(tree, mesh: Mesh):
    """Place a pytree replicated on every device of ``mesh``."""
    return jax.device_put(tree, NamedSharding(mesh, P()))
