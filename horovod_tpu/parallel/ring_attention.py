"""Ring attention: exact attention over sequences sharded across devices.

No reference counterpart — Horovod 0.18.2 is data-parallel only (SURVEY §5
"Long-context: absent") — but long-context sequence parallelism is first-class
in this framework. Design follows the blockwise ring-attention construction
(Liu et al., "Ring Attention with Blockwise Transformers"; see PAPERS.md):

  * Q, K, V are sharded on the sequence axis across the ``sp`` mesh axis.
  * Each step computes a flash-style partial attention (running max ``m``,
    normalizer ``l``, accumulator ``o``) against the currently-held K/V block,
    then rotates K/V one hop around the ring with ``lax.ppermute`` — the
    collective rides ICI neighbor links, overlapping compute with transfer
    (XLA schedules the ppermute DMA alongside the matmuls).
  * After ``sp`` steps every query block has attended to every key block;
    memory per chip stays O(T/sp · T/sp) instead of O(T²).

Causal masking uses global positions derived from each block's ring origin, so
the result matches full causal attention exactly.

The per-hop block compute runs as a Pallas flash kernel
(`horovod_tpu/ops/pallas_kernels.py`) when shapes are MXU-tile-aligned on the
TPU backend (``HVD_PALLAS`` gates it), with this file's jnp flash step as the
always-available fallback — same (m, l, o) carry either way. The backward is
ring-structured too (`_ring_fa_vjp`): a second ring pass runs the Pallas
FlashAttention-2 dq/dkv kernels per hop and rotates the dk/dv accumulator
with its block, so residual memory stays O(T/sp) per chip instead of the
[T/sp, T/sp] score tensors a per-hop jnp VJP would materialize.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

def _block_attn(q, k, v, m, l, o, q_off, k_off, causal, scale):
    """One flash-accumulation step of q against the (k, v) block.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; m/l: [B, H, Tq]; o like q (f32).
    """
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf)  # [B,H,Tq,Tk]
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(tq)
        kpos = k_off + jnp.arange(tk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows: exp(-inf - -inf) etc.
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(jnp.where(jnp.isneginf(s), -jnp.inf, s - m_safe[..., None]))
    alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _ring_fwd_stats(q, k, v, axis_name, step):
    """Forward ring pass: per-hop flash accumulation + K/V rotation.
    Returns the raw (m, l, o) statistics."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    m0 = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    o0 = jnp.zeros((b, t, h, d), jnp.float32)
    q_off = my * t
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(i, carry):
        m, l, o, kv_cur = carry
        # block currently held arrived from rank (my - i) mod n
        src = (my - i) % n
        m, l, o = step(q, kv_cur[0], kv_cur[1], m, l, o, q_off, src * t)
        # rotate K and V to the next rank as ONE stacked buffer: a single
        # collective launch per hop, one large DMA for XLA to overlap with
        # the block matmuls
        kv_nxt = lax.ppermute(kv_cur, axis_name, perm)
        return m, l, o, kv_nxt

    kv0 = jnp.stack([k, v])
    # blocks 0..n-2 rotate; the final block is processed outside the loop so
    # no wasted ppermute trails the last compute step
    m, l, o, kv_last = lax.fori_loop(0, n - 1, body, (m0, l0, o0, kv0))
    src = (my - (n - 1)) % n
    m, l, o = step(q, kv_last[0], kv_last[1], m, l, o, q_off, src * t)
    return m, l, o


@functools.lru_cache(maxsize=None)
def _ring_fa_vjp(axis_name: str, causal: bool, scale: float):
    """Ring attention with a ring-structured FlashAttention-2 backward.

    Forward: Pallas flash step per hop, saving only (q, k, v, out, LSE) —
    O(T/sp) residuals per chip. Backward: a SECOND ring pass — each hop
    runs the Pallas dq and dkv kernels against the visiting K/V block with
    the global row-LSE, accumulates dq locally, and rotates the (dk, dv)
    accumulator WITH the block so every block's gradient arrives back at
    its owner after n hops (the Liu et al. ring-attention backward). This
    replaces differentiating through the forward loop, whose per-hop jnp
    VJP materialized [T/sp, T/sp] score tensors in HBM.
    """
    from ..ops import pallas_kernels as pk

    def fwd_impl(q, k, v):
        def step(qq, kk, vv, m, l, o, q_off, k_off):
            return pk.flash_attention_step(qq, kk, vv, m, l, o, q_off, k_off,
                                           causal=causal, scale=scale)

        m, l, o = _ring_fwd_stats(q, k, v, axis_name, step)
        return pk.finalize_attention_stats(m, l, o, q.dtype)

    @jax.custom_vjp
    def fa(q, k, v):
        return fwd_impl(q, k, v)[0]

    def fwd(q, k, v):
        out, lse = fwd_impl(q, k, v)
        return out, (q, k, v, out, lse)

    def bwd(res, dout):
        q, k, v, out, lse = res
        n = lax.psum(1, axis_name)
        my = lax.axis_index(axis_name)
        t = q.shape[1]
        q_off = my * t
        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(i, carry):
            dq, kv_cur, dkv_cur = carry
            src = (my - i) % n
            dq_i, dk_i, dv_i = pk._flash_bwd(
                q, kv_cur[0], kv_cur[1], out, lse, dout, q_off, src * t,
                causal=causal, scale=scale)
            dq = dq + dq_i
            dkv_cur = dkv_cur + jnp.stack([dk_i, dv_i])
            # n rotations total: the dk/dv accumulator travels with its
            # block and lands back on the block's owner after the loop.
            # Two launches per hop (not one stacked buffer like the
            # forward): the accumulator must stay f32 — n hops of bf16
            # accumulation would degrade the summed gradient — so the
            # dtypes differ; stacking everything in f32 would move MORE
            # bytes (16 vs 12 per element) than the extra launch costs.
            kv_nxt = lax.ppermute(kv_cur, axis_name, perm)
            dkv_nxt = lax.ppermute(dkv_cur, axis_name, perm)
            return dq, kv_nxt, dkv_nxt

        dq0 = jnp.zeros(q.shape, jnp.float32)
        dkv0 = jnp.zeros((2,) + k.shape, jnp.float32)
        dq, _, dkv = lax.fori_loop(0, n, body, (dq0, jnp.stack([k, v]), dkv0))
        return (dq.astype(q.dtype), dkv[0].astype(k.dtype),
                dkv[1].astype(v.dtype))

    fa.defvjp(fwd, bwd)
    return fa


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   scale: Optional[float] = None, use_pallas: bool = True):
    """Exact (flash-accumulated) attention across a sequence-sharded ring.

    Call inside ``shard_map`` with q/k/v sharded on dim 1 (sequence) over
    ``axis_name``. Shapes per shard: ``[batch, seq/sp, heads, head_dim]``.
    Returns the attention output in the input dtype, same sharding.
    ``use_pallas=False`` forces the jnp block path — needed where a Pallas
    custom call cannot be partitioned (heads sharded over a GSPMD auto
    axis, `parallel/hybrid.py`).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5

    from ..ops import pallas_kernels

    if use_pallas and pallas_kernels.step_supported(q, k):
        # Pallas forward AND ring-structured Pallas backward (the blockwise
        # backward kernels cover any shard length — resident or streaming)
        return _ring_fa_vjp(axis_name, causal, float(scale))(q, k, v)

    def step(qq, kk, vv, m, l, o, q_off, k_off):
        return _block_attn(qq, kk, vv, m, l, o, q_off, k_off, causal,
                           scale)

    m, l, o = _ring_fwd_stats(q, k, v, axis_name, step)
    l_safe = jnp.where(l == 0, 1.0, l)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_ring_attention(mesh, axis_name: str = "sp", causal: bool = False):
    """Jitted ring attention over ``mesh``: takes global [B, T, H, D] arrays
    sharded on T and returns the same."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name)

    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return jax.jit(fn)


def reference_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
    """Plain full attention (for tests / single-device fallback)."""
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
