"""Parallelism beyond DP: sequence/context parallelism and two-level
collectives (extensions over the DP-only reference; SURVEY §5)."""

from .hierarchical import (  # noqa: F401
    hierarchical_allreduce,
    make_hierarchical_allreduce,
    make_two_level_mesh,
    stack_contributions,
)
from .ring_attention import (  # noqa: F401
    make_ring_attention,
    reference_attention,
    ring_attention,
)
from .sp_training import (  # noqa: F401
    make_dp_sp_mesh,
    make_sp_forward,
    make_sp_train_step,
    replicate_to_mesh,
    sp_model,
)
from .sequence import (  # noqa: F401
    heads_to_seq,
    make_ulysses_attention,
    seq_to_heads,
    ulysses_attention,
)
from .tensor import (  # noqa: F401
    make_dp_tp_mesh,
    make_tp_train_step,
    plain_attention,
    shard_batch_dp,
    shard_params_tp,
    tp_param_shardings,
)
from .expert import (  # noqa: F401
    MoEMLP,
    make_dp_ep_mesh,
    make_ep_train_step,
    shard_params_ep,
)
from .hybrid import (  # noqa: F401
    hybrid_model,
    make_dp_tp_sp_mesh,
    make_hybrid_train_step,
    shard_data_hybrid,
    shard_opt_state_hybrid,
    shard_params_hybrid,
)
from .pipeline import (  # noqa: F401
    make_pipeline_fn,
    make_pp_mesh,
    make_pp_train_step,
    shard_stage_params,
    stack_stage_params,
)
