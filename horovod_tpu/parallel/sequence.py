"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

No reference counterpart (Horovod 0.18.2 is DP-only); this implements the
DeepSpeed-Ulysses construction on XLA collectives: attention needs full
sequence per head, so before attention an all-to-all converts
sequence-sharding into head-sharding (each device gets ALL tokens for H/sp
heads), and after attention a second all-to-all converts back. Both
all-to-alls ride ICI via ``lax.all_to_all`` inside ``shard_map``.

Use when head count >= sp size; for longer-than-heads scaling use
:mod:`ring_attention`.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
from jax import lax


def seq_to_heads(x, axis_name: str = "sp"):
    """[B, T/sp, H, D] → [B, T, H/sp, D]: gather sequence, scatter heads."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def heads_to_seq(x, axis_name: str = "sp"):
    """[B, T, H/sp, D] → [B, T/sp, H, D]: inverse reshard."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                      attn_fn: Optional[Callable] = None):
    """Attention over sequence-sharded q/k/v ([B, T/sp, H, D] per shard) via
    the Ulysses two-all-to-all pattern. ``attn_fn(q, k, v, causal=...)``
    computes full attention on [B, T, H/sp, D] (default: the Pallas flash
    kernel with its FlashAttention-2 backward, which itself falls back to
    exact jnp attention when shapes/gating rule it out)."""
    from ..ops.pallas_kernels import flash_attention

    attn_fn = attn_fn or flash_attention
    qh = seq_to_heads(q, axis_name)
    kh = seq_to_heads(k, axis_name)
    vh = seq_to_heads(v, axis_name)
    oh = attn_fn(qh, kh, vh, causal=causal)
    return heads_to_seq(oh, axis_name)


def make_ulysses_attention(mesh, axis_name: str = "sp",
                           causal: bool = False):
    """Jitted Ulysses attention over ``mesh``: global [B, T, H, D] sharded on
    T in, same out. Requires H % sp == 0."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name)
    fn = jax.shard_map(
        functools.partial(ulysses_attention, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return jax.jit(fn)
