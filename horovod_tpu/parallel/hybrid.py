"""3D hybrid parallelism: data x tensor x sequence in ONE mesh.

No reference counterpart (Horovod 0.18.2 is data-parallel only); this is the
composition layer over the framework's parallel building blocks, in the
"How to Scale Your Model" style: ONE ``("dp", "tp", "sp")`` mesh, each axis
owned by the partitioning mode that suits it —

  * **dp** (manual): batch sharded; gradients ``pmean`` across it.
  * **sp** (manual): sequence sharded; ring attention rotates K/V blocks via
    ``lax.ppermute`` neighbor hops (`ring_attention.py`).
  * **tp** (automatic): Megatron-style column/row-parallel parameters via
    GSPMD sharding propagation (`tensor.py` param specs) — the row-parallel
    psums and tensor-gradient reductions are compiler-inserted.

The mechanism is jax's partial-manual ``shard_map``: ``axis_names={"dp",
"sp"}`` makes dp/sp manual (explicit collectives legal) while tp stays an
*auto* axis — parameters keep their GSPMD shardings straight through the
manual region, so tensor parallelism needs no hand-written collectives and
composes with the manual ring.

On real hardware lay ``sp`` along an ICI ring (neighbor hops) and ``tp``
within a slice; ``dp`` can span DCN. Note on kernels: a Pallas attention
kernel is a custom call GSPMD cannot partition over the auto tp axis, so
inside the hybrid step the attention runs the jnp ring path (the manual-sp
ring still bounds activations at O(T/sp)).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ring_attention import ring_attention
from .sp_training import make_sp_train_step
from .tensor import shard_params_tp

DP_AXIS, TP_AXIS, SP_AXIS = "dp", "tp", "sp"


def make_dp_tp_sp_mesh(dp: int, tp: int, sp: int, devices=None) -> Mesh:
    devices = list(jax.devices() if devices is None else devices)
    n = dp * tp * sp
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:n]).reshape(dp, tp, sp),
                (DP_AXIS, TP_AXIS, SP_AXIS))


def hybrid_model(model_cls, **kwargs):
    """Model with ring attention over ``sp`` on the jnp block path
    (``use_pallas=False``: a Pallas custom call cannot be GSPMD-partitioned
    over the auto tp axis; the jnp einsums can)."""
    attn = partial(ring_attention, axis_name=SP_AXIS, causal=True,
                   use_pallas=False)
    return model_cls(attn_fn=attn, **kwargs)


def shard_params_hybrid(params, mesh: Mesh):
    """Place params with the Megatron column/row specs over ``tp``."""
    return shard_params_tp(params, mesh, TP_AXIS)


def shard_opt_state_hybrid(opt_state, params, mesh: Mesh):
    """Place optimizer state so PARAM-STRUCTURED subtrees (Adam's m/v,
    momentum traces — optax states embed copies of the param tree) follow
    their parameter's Megatron tp spec; everything else (step counts,
    schedules) replicates. tp is the AUTO axis, so sharded state flows
    through the hybrid step exactly like the params do."""
    from .tensor import tp_param_shardings

    param_sh = tp_param_shardings(params, mesh, TP_AXIS)
    p_def = jax.tree_util.tree_structure(params)
    repl = NamedSharding(mesh, P())

    def is_param_tree(x):
        return jax.tree_util.tree_structure(x) == p_def

    def place(node):
        if is_param_tree(node):
            return jax.tree_util.tree_map(jax.device_put, node, param_sh)
        return jax.device_put(node, repl)

    return jax.tree_util.tree_map(place, opt_state, is_leaf=is_param_tree)


def shard_data_hybrid(tokens, mesh: Mesh):
    """Global [B, T] int arrays -> batch over dp, sequence over sp."""
    return jax.device_put(tokens, NamedSharding(mesh, P(DP_AXIS, SP_AXIS)))


def make_hybrid_train_step(model, tx, mesh: Mesh) -> Callable:
    """Jitted 3D-parallel step: ``(params, opt_state, tokens, targets) ->
    (params, opt_state, loss)`` with tokens/targets GLOBAL [B, T].

    Parameter/optimizer trees may carry tp shardings (see
    :func:`shard_params_hybrid`); they flow through the manual region as
    auto-axis shardings and the step's outputs preserve them.
    """
    # the sp step body IS the hybrid step body: only the manual-axis set
    # differs (tp stays automatic so GSPMD keeps the tensor shardings)
    return make_sp_train_step(model, tx, mesh, dp_axis=DP_AXIS,
                              sp_axis=SP_AXIS,
                              manual_axes={DP_AXIS, SP_AXIS})
