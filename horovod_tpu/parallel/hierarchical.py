"""Hierarchical (two-level) collectives: ICI within a slice, DCN across.

Reference parity: `NCCLHierarchicalAllreduce` (`nccl_operations.cc:150-346`):
intra-node ncclReduceScatter → cross-node MPI_Allreduce → intra-node
ncclAllGather, with the LOCAL/CROSS communicator split of
`mpi_context.cc:150-158`. TPU-native: the mesh carries both axes —
``("dcn", "ici")`` — LOCAL=ici rides the intra-slice interconnect and
CROSS=dcn the data-center network; the decomposition is expressed with XLA
collectives and GSPMD schedules both legs.

Note XLA already decomposes a plain ``psum(x, ("dcn", "ici"))`` near-optimally
on real topologies; the explicit form exists for parity, for bandwidth shaping
(scatter dimension choice), and as the building block for the cross-slice
eager path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_two_level_mesh(ici_size: Optional[int] = None,
                        devices=None) -> Mesh:
    """Build a ("dcn", "ici") mesh. Each ici row is one process's devices
    (one host's chips — ICI domain); rows are hosts (DCN domain). Device
    order from ``jax.devices()`` is NOT assumed process-contiguous — rows are
    built from explicit per-process grouping. Pass ``ici_size`` to subdivide
    differently (must evenly divide each process's device count)."""
    devices = list(devices) if devices is not None else list(jax.devices())
    per_proc = {}
    for d in devices:
        per_proc.setdefault(d.process_index, []).append(d)
    groups = [per_proc[k] for k in sorted(per_proc)]
    if ici_size is None:
        ici_size = len(groups[0])
    rows = []
    for g in groups:
        if len(g) % ici_size != 0:
            raise ValueError(
                f"process owns {len(g)} devices, not divisible by "
                f"ici_size={ici_size}")
        for i in range(0, len(g), ici_size):
            rows.append(g[i:i + ici_size])
    return Mesh(np.asarray(rows, dtype=object), ("dcn", "ici"))


def hierarchical_allreduce(x, ici_axis: str = "ici", dcn_axis: str = "dcn",
                           average: bool = False):
    """reduce_scatter(ICI) → allreduce(DCN) → all_gather(ICI), the
    NCCLHierarchicalAllreduce decomposition. Call inside shard_map over a
    two-axis mesh with ``x`` = this device's same-shaped contribution.
    Dim 0 is padded to ici-divisibility internally (the reference pads to
    fp64-worst-case divisibility, nccl_operations.cc:198-204)."""
    ici = lax.psum(1, ici_axis)
    d0 = x.shape[0]
    pad = (-d0) % ici
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
    scattered = lax.psum_scatter(x, ici_axis, scatter_dimension=0, tiled=True)
    reduced = lax.psum(scattered, dcn_axis)
    out = lax.all_gather(reduced, ici_axis, axis=0, tiled=True)
    if pad:
        out = out[:d0]
    if average:
        n = ici * lax.psum(1, dcn_axis)
        out = out / jnp.asarray(n, out.dtype)
    return out


def make_hierarchical_allreduce(mesh: Mesh, average: bool = False):
    """Jitted two-level allreduce of PER-DEVICE contributions.

    Input: a global array of shape ``[n_devices, ...]`` sharded on dim 0 over
    both mesh axes — row i is device i's contribution. Output: the full
    reduction, replicated on every device (shape ``[...]``).
    """
    dcn_axis, ici_axis = mesh.axis_names

    def body(x):  # x: [1, ...] — this device's row
        if x.shape[0] != 1:
            raise ValueError(
                f"make_hierarchical_allreduce expects dim 0 == n_devices "
                f"({mesh.size}); got a per-device shard of {x.shape[0]} rows "
                "— extra rows would be silently dropped")
        return hierarchical_allreduce(x[0], ici_axis=ici_axis,
                                      dcn_axis=dcn_axis, average=average)

    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=P((dcn_axis, ici_axis)), out_specs=P(),
                       check_vma=False)
    return jax.jit(fn)


def stack_contributions(mesh: Mesh, arrays):
    """Helper: place per-device host arrays as the sharded [n, ...] input of
    :func:`make_hierarchical_allreduce` (device i gets ``arrays[i]``)."""
    devs = list(mesh.devices.flat)
    assert len(arrays) == len(devs)
    shards = [jax.device_put(np.asarray(a)[None], d)
              for a, d in zip(arrays, devs)]
    shape = (len(devs),) + tuple(np.shape(arrays[0]))
    sharding = NamedSharding(mesh, P(mesh.axis_names))
    return jax.make_array_from_single_device_arrays(shape, sharding, shards)
