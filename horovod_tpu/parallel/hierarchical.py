"""Hierarchical (two-level) collectives: ICI within a slice, DCN across.

Reference parity: `NCCLHierarchicalAllreduce` (`nccl_operations.cc:150-346`):
intra-node ncclReduceScatter → cross-node MPI_Allreduce → intra-node
ncclAllGather, with the LOCAL/CROSS communicator split of
`mpi_context.cc:150-158`. TPU-native: the mesh carries both axes —
``("dcn", "ici")`` — LOCAL=ici rides the intra-slice interconnect and
CROSS=dcn the data-center network; the decomposition is expressed with XLA
collectives and GSPMD schedules both legs.

Note XLA already decomposes a plain ``psum(x, ("dcn", "ici"))`` near-optimally
on real topologies; the explicit form exists for parity, for bandwidth shaping
(scatter dimension choice), and as the building block for the cross-slice
eager path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import numpy as np


def make_two_level_mesh(ici_size: Optional[int] = None,
                        devices=None) -> Mesh:
    """Build a ("dcn", "ici") mesh: ici = devices per slice (defaults to the
    devices of one process = one host's chips), dcn = slices."""
    devices = list(devices) if devices is not None else list(jax.devices())
    if ici_size is None:
        per_proc = {}
        for d in devices:
            per_proc.setdefault(d.process_index, []).append(d)
        ici_size = len(next(iter(per_proc.values())))
    n = len(devices)
    assert n % ici_size == 0, (n, ici_size)
    arr = np.asarray(devices).reshape(n // ici_size, ici_size)
    return Mesh(arr, ("dcn", "ici"))


def hierarchical_allreduce(x, ici_axis: str = "ici", dcn_axis: str = "dcn",
                           average: bool = False):
    """reduce_scatter(ICI) → allreduce(DCN) → all_gather(ICI), the
    NCCLHierarchicalAllreduce decomposition. Call inside shard_map over a
    two-axis mesh. ``x`` must have dim 0 divisible by the ici axis size
    (the reference pads to fp64-worst-case divisibility,
    nccl_operations.cc:198-204; here the caller pads)."""
    scattered = lax.psum_scatter(x, ici_axis, scatter_dimension=0, tiled=True)
    reduced = lax.psum(scattered, dcn_axis)
    out = lax.all_gather(reduced, ici_axis, axis=0, tiled=True)
    if average:
        n = lax.psum(1, ici_axis) * lax.psum(1, dcn_axis)
        out = out / jnp.asarray(n, out.dtype)
    return out


def make_hierarchical_allreduce(mesh: Mesh, average: bool = False):
    """Jitted two-level allreduce: every device holds the full (replicated)
    reduced array afterwards."""
    dcn_axis, ici_axis = mesh.axis_names

    fn = jax.shard_map(
        functools.partial(hierarchical_allreduce, ici_axis=ici_axis,
                          dcn_axis=dcn_axis, average=average),
        mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
    return jax.jit(fn)
