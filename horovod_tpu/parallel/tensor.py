"""Tensor (model) parallelism for the transformer — Megatron-style sharding
expressed through GSPMD.

Beyond-reference extension (the reference is DP-only, SURVEY honesty note):
instead of hand-written collective calls, the parameters carry
`PartitionSpec`s over a ``("dp", "tp")`` mesh and XLA inserts the
collectives — column-parallel qkv/mlp_in (output features sharded over
``tp``), row-parallel proj/mlp_out (input features sharded, psum on the
output), LayerNorms/embeddings replicated. Attention runs head-parallel
for free: the qkv feature shard IS the head shard after the reshape.

Use :func:`plain_attention` as the model's ``attn_fn`` under TP — the
Pallas flash kernel is a custom call GSPMD cannot repartition.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ring_attention import reference_attention

#: Causal attention in pure lax ops (GSPMD-partitionable, fp32 softmax).
plain_attention = functools.partial(reference_attention, causal=True)


def make_2d_mesh(axes: tuple, sizes: tuple, devices=None) -> Mesh:
    """(dp, X) mesh factory shared by the tp/ep variants."""
    devices = list(devices) if devices is not None else list(jax.devices())
    total = sizes[0] * sizes[1]
    if total > len(devices):
        raise ValueError(
            f"{axes[0]}*{axes[1]}={total} exceeds {len(devices)} devices")
    return Mesh(np.asarray(devices[:total]).reshape(sizes), axes)


def make_dp_tp_mesh(dp: int, tp: int, devices=None) -> Mesh:
    return make_2d_mesh(("dp", "tp"), (dp, tp), devices)


def make_sharded_train_step(loss_fn: Callable, tx, mesh: Mesh,
                            batch_axis: str = "dp") -> Callable:
    """Jitted train step for mesh-sharded params (tp/ep/...): params and
    optimizer state inherit their input shardings (initialize
    ``opt_state = tx.init(sharded_params)``); the batch is pinned to
    ``batch_axis`` so unsharded callers are resharded rather than silently
    running data-serial. ``loss_fn(params, batch) -> scalar``."""
    import optax

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, in_shardings=(
        None, None, NamedSharding(mesh, P(batch_axis))))


def tp_param_spec(path_keys, leaf, tp_axis: str = "tp") -> P:
    """PartitionSpec for one transformer parameter, by its tree path.

    Column-parallel (shard OUTPUT features): qkv, mlp_in.
    Row-parallel (shard INPUT features, psum after): proj, mlp_out.
    Everything else (LayerNorm, embeddings, pos table, head) replicated.
    """
    names = [str(k) for k in path_keys]
    owner = next((n for n in ("qkv", "mlp_in", "proj", "mlp_out")
                  if n in names), None)
    is_kernel = names[-1] == "kernel"
    if owner in ("qkv", "mlp_in"):
        return P(None, tp_axis) if is_kernel else P(tp_axis)
    if owner in ("proj", "mlp_out"):
        # row-parallel bias is applied AFTER the psum — replicated
        return P(tp_axis, None) if is_kernel else P()
    return P()


def tp_param_shardings(params, mesh: Mesh, tp_axis: str = "tp"):
    """Pytree of NamedShardings matching :func:`tp_param_spec`; validates
    that sharded feature dims divide by the tp size."""
    tp = mesh.shape[tp_axis]

    def one(path, leaf):
        spec = tp_param_spec([p.key if hasattr(p, "key") else p.name
                              for p in path], leaf, tp_axis)
        for dim, axis in enumerate(spec):
            if axis == tp_axis and leaf.shape[dim] % tp != 0:
                raise ValueError(
                    f"parameter {'/'.join(str(p) for p in path)} dim {dim} "
                    f"({leaf.shape[dim]}) not divisible by tp={tp}")
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params_tp(params, mesh: Mesh, tp_axis: str = "tp"):
    """Place a replicated/host param tree onto the mesh with TP sharding."""
    sh = tp_param_shardings(params, mesh, tp_axis)
    return jax.tree_util.tree_map(jax.device_put, params, sh)


def make_tp_train_step(loss_fn: Callable, tx, mesh: Mesh,
                       dp_axis: str = "dp", tp_axis: str = "tp") -> Callable:
    """TP train step: GSPMD inserts the row-parallel psums and the cross-dp
    gradient reduction (see :func:`make_sharded_train_step`)."""
    return make_sharded_train_step(loss_fn, tx, mesh, batch_axis=dp_axis)


def shard_batch_dp(batch, mesh: Mesh, dp_axis: str = "dp"):
    sh = NamedSharding(mesh, P(dp_axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)
