"""In-process local cluster: run a function as N ranks on N local devices.

This is the TPU-native analogue of ``horovodrun -np N -H localhost:N`` used to
run the reference's whole test matrix on one machine
(`.buildkite/gen-pipeline.sh:104-200`, `test/common.py:24-56`). Instead of N
OS processes coordinated over Gloo, N *threads* each bind to one local device
(rank i ↔ device i) and share the in-process engine — the negotiation, fusion,
validation, join and error paths are exercised exactly as in the reference's
multi-process runs, while the collective itself executes as one XLA program
over the device mesh.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from . import basics


class _RankThread(threading.Thread):
    def __init__(self, rank: int, fn: Callable, args, kwargs):
        super().__init__(name=f"hvd_tpu_rank{rank}", daemon=True)
        self.rank = rank
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.result: Any = None
        self.error: Optional[BaseException] = None

    def run(self):
        basics.set_thread_rank(self.rank)
        try:
            self.result = self.fn(*self.args, **self.kwargs)
        except BaseException as e:  # propagate to the launcher
            self.error = e


def run_cluster(fn: Callable, np: int = 2, args: Sequence = (),
                kwargs: Optional[dict] = None,
                timeout: float = 300.0) -> List[Any]:
    """Run ``fn`` once per rank (N threads, one per device); returns per-rank
    results in rank order. Initializes the framework in cluster mode if needed;
    raises the first rank failure (first-failure semantics like
    `gloo_run.py:253-259`)."""
    kwargs = kwargs or {}
    if basics.is_initialized():
        st = basics._state
        if st.mode != "cluster" or st.size != np:
            basics.shutdown()
    if not basics.is_initialized():
        basics.init(_cluster_size=np)
    threads = [_RankThread(r, fn, args, kwargs) for r in range(np)]
    for t in threads:
        t.start()
    # poll rather than join in rank order: a rank that died on an exception
    # usually stalls its peers' collectives, and waiting out the full timeout
    # on a hung peer would mask the root-cause error (first-failure
    # semantics like gloo_run.py:253-259)
    # one shared deadline for the whole cluster, but scaled with np: every
    # rank's work is serialized onto the same host under load (full-suite CI
    # runs), so a fixed budget that is ample at np=2 can spuriously trip at
    # np=8
    budget = timeout * max(1.0, np / 2.0)
    deadline = time.monotonic() + budget
    while True:
        alive = [t for t in threads if t.is_alive()]
        failed = [t for t in threads if not t.is_alive() and t.error]
        if failed and alive:
            raise failed[0].error
        if not alive:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"rank(s) {[t.rank for t in alive]} did not finish within "
                f"{budget:g}s (timeout={timeout:g}s scaled by np={np}; "
                f"possible stalled negotiation)")
        alive[0].join(timeout=0.05)
    for t in threads:
        if t.error is not None:
            raise t.error
    return [t.result for t in threads]


def eager_dryrun_worker():
    """Per-process body of the driver gate's negotiated-engine leg
    (``__graft_entry__._dryrun_eager_leg``): fused allreduce, ragged
    allgather and join through the coordinated engine. Lives here so it
    pickles by importable reference (the launcher's stdlib-pickle fallback
    cannot ship script-``__main__`` functions)."""
    import numpy as np

    from . import basics
    from .ops import collective_ops as C

    r = basics.rank()
    outs = {}
    # three tensors in flight at once: the coordinator fuses same-signature
    # requests under the threshold into one response
    hs = [C.allreduce_async(np.full((32,), float(r + i), np.float32),
                            name=f"dr{i}", op=basics.Sum) for i in range(3)]
    outs["ar"] = [float(np.asarray(C.synchronize(h))[0]) for h in hs]
    # ragged allgather: rank r contributes r+1 rows
    g = C.allgather_async(np.full((r + 1, 2), float(r), np.float32),
                          name="drg")
    outs["ag"] = np.asarray(C.synchronize(g)).tolist()
    # uneven data + join: rank 0 runs one extra allreduce; the joined rank 1
    # contributes zeros
    if r == 0:
        h = C.allreduce_async(np.full((4,), 5.0, np.float32), name="drj",
                              op=basics.Sum)
        outs["post"] = float(np.asarray(C.synchronize(h))[0])
    outs["last"] = C.join()
    return (r, outs)
