"""In-process local cluster: run a function as N ranks on N local devices.

This is the TPU-native analogue of ``horovodrun -np N -H localhost:N`` used to
run the reference's whole test matrix on one machine
(`.buildkite/gen-pipeline.sh:104-200`, `test/common.py:24-56`). Instead of N
OS processes coordinated over Gloo, N *threads* each bind to one local device
(rank i ↔ device i) and share the in-process engine — the negotiation, fusion,
validation, join and error paths are exercised exactly as in the reference's
multi-process runs, while the collective itself executes as one XLA program
over the device mesh.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from . import basics


class _RankThread(threading.Thread):
    def __init__(self, rank: int, fn: Callable, args, kwargs):
        super().__init__(name=f"hvd_tpu_rank{rank}", daemon=True)
        self.rank = rank
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.result: Any = None
        self.error: Optional[BaseException] = None

    def run(self):
        basics.set_thread_rank(self.rank)
        try:
            self.result = self.fn(*self.args, **self.kwargs)
        except BaseException as e:  # propagate to the launcher
            self.error = e


def run_cluster(fn: Callable, np: int = 2, args: Sequence = (),
                kwargs: Optional[dict] = None,
                timeout: float = 300.0) -> List[Any]:
    """Run ``fn`` once per rank (N threads, one per device); returns per-rank
    results in rank order. Initializes the framework in cluster mode if needed;
    raises the first rank failure (first-failure semantics like
    `gloo_run.py:253-259`)."""
    kwargs = kwargs or {}
    if basics.is_initialized():
        st = basics._state
        if st.mode != "cluster" or st.size != np:
            basics.shutdown()
    if not basics.is_initialized():
        basics.init(_cluster_size=np)
    threads = [_RankThread(r, fn, args, kwargs) for r in range(np)]
    for t in threads:
        t.start()
    # poll rather than join in rank order: a rank that died on an exception
    # usually stalls its peers' collectives, and waiting out the full timeout
    # on a hung peer would mask the root-cause error (first-failure
    # semantics like gloo_run.py:253-259)
    # one shared deadline for the whole cluster, but scaled with np: every
    # rank's work is serialized onto the same host under load (full-suite CI
    # runs), so a fixed budget that is ample at np=2 can spuriously trip at
    # np=8
    budget = timeout * max(1.0, np / 2.0)
    deadline = time.monotonic() + budget
    while True:
        alive = [t for t in threads if t.is_alive()]
        failed = [t for t in threads if not t.is_alive() and t.error]
        if failed and alive:
            raise failed[0].error
        if not alive:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"rank(s) {[t.rank for t in alive]} did not finish within "
                f"{budget:g}s (timeout={timeout:g}s scaled by np={np}; "
                f"possible stalled negotiation)")
        alive[0].join(timeout=0.05)
    for t in threads:
        if t.error is not None:
            raise t.error
    return [t.result for t in threads]


def eager_dryrun_worker():
    """Per-process body of the driver gate's negotiated-engine leg
    (``__graft_entry__._dryrun_eager_leg``): fused allreduce, ragged
    allgather and join through the coordinated engine. Lives here so it
    pickles by importable reference (the launcher's stdlib-pickle fallback
    cannot ship script-``__main__`` functions)."""
    import numpy as np

    from . import basics
    from .ops import collective_ops as C

    r = basics.rank()
    outs = {}
    # three tensors in flight at once: the coordinator fuses same-signature
    # requests under the threshold into one response
    hs = [C.allreduce_async(np.full((32,), float(r + i), np.float32),
                            name=f"dr{i}", op=basics.Sum) for i in range(3)]
    outs["ar"] = [float(np.asarray(C.synchronize(h))[0]) for h in hs]
    # ragged allgather: rank r contributes r+1 rows
    g = C.allgather_async(np.full((r + 1, 2), float(r), np.float32),
                          name="drg")
    outs["ag"] = np.asarray(C.synchronize(g)).tolist()
    # uneven data + join: rank 0 runs one extra allreduce; the joined rank 1
    # contributes zeros
    if r == 0:
        h = C.allreduce_async(np.full((4,), 5.0, np.float32), name="drj",
                              op=basics.Sum)
        outs["post"] = float(np.asarray(C.synchronize(h))[0])
    outs["last"] = C.join()
    return (r, outs)


def hier_dryrun_worker():
    """Driver-gate leg body: fused allreduce, ragged allgather and ragged
    alltoall through the coordinated engine — run once on the flat rank
    mesh and once over the 2x2 two-level ("dcn","ici") mesh
    (HOROVOD_HIERARCHICAL_* legs of ``dryrun_multichip``); results must be
    identical (small-integer inputs: exact in any association order)."""
    import numpy as np

    from . import basics
    from .ops import collective_ops as C

    r = basics.rank()
    w = basics.size()
    outs = {}
    hs = [C.allreduce_async(np.arange(17, dtype=np.float32) + r + i,
                            name=f"hd{i}", op=basics.Sum) for i in range(3)]
    outs["ar"] = [np.asarray(C.synchronize(h)).tolist() for h in hs]
    g = C.allgather_async(np.full((r + 1, 2), float(r), np.float32),
                          name="hdg")
    outs["ag"] = np.asarray(C.synchronize(g)).tolist()
    splits = [(r + d) % 2 + 1 for d in range(w)]
    rows = [[10.0 * r + d] for d in range(w) for _ in range(splits[d])]
    a2av_out, a2av_rs = C.alltoall(np.asarray(rows, np.float32),
                                   splits=splits, name="hdv")
    outs["a2av"] = np.asarray(a2av_out).tolist()
    outs["a2av_rs"] = np.asarray(a2av_rs).tolist()
    # report whether the executor REALLY took the two-level path, so the
    # gate can reject a vacuous flat-vs-flat comparison
    ex = basics._engine()._executor
    two_level = bool(ex._mesh2 is not None and ex._hier_allreduce
                     and ex._hier_allgather)
    return (r, two_level, outs)


def autotune_dryrun_worker():
    """Driver-gate leg body: the HOROVOD_AUTOTUNE leg — same collectives
    under GP/EI tuning started at a 1-byte fusion threshold with tight
    cadence knobs; returns the results plus (start, end) threshold so the
    gate can assert the tuned parameters moved."""
    import numpy as np

    from . import basics
    from .ops import collective_ops as C

    eng = basics._engine()
    start = eng.controller.fusion_threshold()
    data = [np.full((4096,), float(basics.rank() + i), np.float32)
            for i in range(6)]

    def round_(t):
        hs = [C.allreduce_async(d, name=f"at{i}", op=basics.Sum)
              for i, d in enumerate(data)]
        return [float(np.asarray(C.synchronize(h))[0]) for h in hs]

    round_(0)  # first execution pays compile; not scored
    outs = None
    for t in range(10):
        outs = round_(t)
    return (basics.rank(), outs, start, eng.controller.fusion_threshold())


def adasum_dryrun_worker():
    """Driver-gate leg body (BASELINE tracked config 5): eager Adasum
    allreduce across 2 real processes through the coordinated engine —
    once plain f32 and once through fp16 wire compression. Returns the
    inputs and outputs so the gate pins the combine against the NumPy
    VHDD oracle (`adasum/adasum.h:185-329` semantics)."""
    import numpy as np

    from . import basics
    from .ops import collective_ops as C
    from .ops.compression import Compression

    r = basics.rank()
    rng = np.random.RandomState(7 + r)
    x = rng.randn(257).astype(np.float32)
    plain = np.asarray(C.allreduce(x, name="adsm", op=basics.Adasum))
    comp = np.asarray(C.allreduce(x, name="adsm16", op=basics.Adasum,
                                  compression=Compression.fp16))
    return (r, x.tolist(), plain.tolist(), comp.tolist())
