"""Deterministic fault execution: per-rank hit counters + socket wrapper.

The :class:`Injector` owns one hit counter per (rule, point) so a spec like
``conn_drop@tick:3`` fires at exactly the third tick of the process it runs
in — deterministic by construction, no randomness anywhere. Frame-granular
kinds (corrupt/truncate/partial, and conn_drop/delay at point ``frame``) are
applied by :class:`FaultSocket`, which wraps the real control-plane socket
and counts every outgoing frame as one hit of point ``frame``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

from .spec import FaultRule

logger = logging.getLogger("horovod_tpu")

# Clock origin for partition activation/heal windows: per-process monotonic,
# anchored at module import so every Injector built in this process (fresh
# for_rank() instances included) sees the same partition schedule.
_PART_T0 = time.monotonic()


class Partition:
    """One active ``partition@net:A|B`` rule: answers "does a frame from
    rank a to rank b cross the cut right now?" and "has this rank lost the
    rendezvous KV?". Deterministic: activation and heal are fixed offsets
    from process start on the local monotonic clock."""

    def __init__(self, rule: FaultRule):
        self._a, self._b = rule.groups
        self._start = _PART_T0 + rule.start
        # seconds == 0 means the partition never heals
        self._heal = self._start + rule.seconds if rule.seconds else None
        self._logged = False

    def active(self) -> bool:
        now = time.monotonic()
        return now >= self._start and (self._heal is None or now < self._heal)

    def blocks(self, sender: Optional[int], peer: Optional[int]) -> bool:
        """Whether a frame from ``sender`` to ``peer`` crosses the cut.
        Unknown peers (None) are never blocked — the caller has no basis to
        attribute the connection to either side."""
        if sender is None or peer is None or sender == peer:
            return False
        cross = ((sender in self._a and peer in self._b) or
                 (sender in self._b and peer in self._a))
        return cross and self.active()

    def blocks_kv(self, rank: int) -> bool:
        """The first group is the minority side: while the partition is
        active it cannot reach the rendezvous KV either (the KV rides with
        the launcher, on the second group's side of the cut)."""
        return rank in self._a and self.active()

    def note_blocked(self, sender: int, peer: int) -> None:
        if self._logged:
            return
        self._logged = True
        logger.warning(
            "faultinject: network partition active — dropping frames "
            "between rank %s and rank %s (and all other cross-group pairs)",
            sender, peer)
        from .. import blackbox
        blackbox.record(blackbox.K_FAULT, "net",
                        "partition blocking rank %d <-> rank %d"
                        % (sender, peer), rank=sender)


class Injector:
    """Executes a parsed fault plan for one rank."""

    def __init__(self, rules: List[FaultRule], rank: int):
        self.rank = rank
        self._rules = [r for r in rules if r.applies_to(rank)]
        self._hits = {}  # id(rule) -> hit count
        self._lock = threading.Lock()
        self._drop_cb: Optional[Callable[[], None]] = None
        parts = [r for r in self._rules if r.kind == "partition"]
        self.partition: Optional[Partition] = (
            Partition(parts[0]) if parts else None)

    def active(self) -> bool:
        return bool(self._rules)

    def set_drop_callback(self, cb: Callable[[], None]) -> None:
        """Register how a point-level ``conn_drop`` severs the connection
        (the controller closes its current socket)."""
        self._drop_cb = cb

    def actions_for(self, point: str) -> List[Tuple[str, float]]:
        """Count one hit of ``point`` and return the (kind, seconds) pairs
        that fire on this hit."""
        fired: List[Tuple[str, float]] = []
        with self._lock:
            for rule in self._rules:
                if rule.point != point:
                    continue
                key = id(rule)
                n = self._hits.get(key, 0) + 1
                self._hits[key] = n
                if rule.kind == "flaky_slow":
                    # deterministic coin flip on the hit index (Knuth
                    # multiplicative hash): same (rule, hit) always decides
                    # the same way, so flaky runs replay exactly
                    u = ((n * 2654435761) % (2 ** 32)) / 2.0 ** 32
                    if u >= rule.prob:
                        continue
                if rule.nth is None or rule.nth == n:
                    fired.append((rule.kind, rule.seconds))
                    logger.warning(
                        "faultinject: rank %s firing %s at %s (hit %d)",
                        self.rank, rule.kind, point, n)
                    from .. import blackbox
                    blackbox.record(
                        blackbox.K_FAULT, point,
                        "%s fired (hit %d, %gs)" % (rule.kind, n,
                                                    rule.seconds),
                        rank=self.rank)
        return fired

    def fire(self, point: str) -> None:
        """Named-point hook (tick/exchange/connect/heartbeat/collective).
        Only ``delay``/``hang`` and ``conn_drop`` are meaningful outside
        the socket wrapper; frame-granular kinds are ignored here. Data-
        plane kinds (``nan``/``desync``) are queried via
        :meth:`actions_for` by the integrity layer, which owns the
        tensors being poisoned."""
        for kind, seconds in self.actions_for(point):
            if kind in ("delay", "hang", "slow", "flaky_slow"):
                time.sleep(seconds)
            elif kind == "conn_drop" and self._drop_cb is not None:
                self._drop_cb()

    def wrap(self, sock) -> "FaultSocket":
        return FaultSocket(sock, self)


class FaultSocket:
    """Socket proxy applying frame-granular faults to each sendall().

    The control plane writes exactly one frame per sendall() call, so a
    ``frame`` hit maps 1:1 onto wire frames. Reads pass through untouched —
    corruption is injected on the sender, where the byte layout is known.
    """

    def __init__(self, sock, injector: Injector):
        self._sock = sock
        self._inj = injector
        self._peer: Optional[int] = None

    def set_peer(self, rank: Optional[int]) -> None:
        """Tell the wrapper which rank sits on the other end, so partition
        rules can decide whether this connection crosses the cut. None =
        unknown (never partitioned)."""
        self._peer = rank

    def sendall(self, data: bytes) -> None:
        part = self._inj.partition
        if part is not None and part.blocks(self._inj.rank, self._peer):
            # the frame is dropped AND the socket severed: the sender sees
            # the loss as a peer reset, driving the reconnect machinery
            # instead of an unbounded recv() hang
            part.note_blocked(self._inj.rank, self._peer)
            self._close_quietly()
            raise ConnectionError(
                "faultinject: network partition between rank %s and rank %s"
                % (self._inj.rank, self._peer))
        for kind, seconds in self._inj.actions_for("frame"):
            if kind == "delay":
                time.sleep(seconds)
            elif kind == "conn_drop":
                # close before sending: this sendall (or the next recv)
                # surfaces the loss exactly as a peer reset would
                self._close_quietly()
            elif kind == "corrupt":
                # flip every bit of the last byte: payload (or MAC) damage
                # the receiver's CRC32/HMAC check must reject. The length
                # prefix is left intact so framing itself survives.
                data = data[:-1] + bytes([data[-1] ^ 0xFF])
            elif kind == "truncate":
                self._sock.sendall(data[:max(1, len(data) // 2)])
                self._close_quietly()
                raise ConnectionError(
                    "faultinject: truncated frame mid-send")
            elif kind == "partial":
                # byte-at-a-time writes: the receiver must loop to the
                # declared length instead of assuming whole-frame reads
                for i in range(0, len(data), 1):
                    self._sock.sendall(data[i:i + 1])
                return
        self._sock.sendall(data)

    def _close_quietly(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __getattr__(self, name):
        return getattr(self._sock, name)
