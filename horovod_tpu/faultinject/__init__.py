"""Env-driven fault injection for the control plane (docs/fault-tolerance.md).

``HOROVOD_FAULT_SPEC`` (grammar in :mod:`.spec`) describes deterministic
faults — connection drops, stalls, partial writes, corrupted/truncated
frames — injected at named points of the coordinator wire on either side.
The harness exists so the hardening in `runtime/coordinator.py` (reconnect,
replay, heartbeats, CRC frame checks) is provable from tests and
``bench.py --chaos`` rather than only observable in production incidents.

Usage from instrumented code::

    faults = faultinject.for_rank(rank)       # None when no spec is set
    if faults is not None:
        faults.fire("tick")                   # named-point hook
        sock = faults.wrap(sock)              # frame-granular faults

The spec is re-read from the environment on every :func:`for_rank` call, so
tests can monkeypatch ``HOROVOD_FAULT_SPEC`` per scenario; with the variable
unset the layer costs one dict lookup and adds nothing to the hot path.
"""

from __future__ import annotations

import os
from typing import Optional

from .injector import FaultSocket, Injector
from .spec import FaultRule, parse_spec

__all__ = ["FaultRule", "FaultSocket", "Injector", "parse_spec", "for_rank"]

ENV_VAR = "HOROVOD_FAULT_SPEC"


def for_rank(rank: int) -> Optional[Injector]:
    """Build this rank's injector from ``HOROVOD_FAULT_SPEC``; None when the
    spec is unset/empty or matches no rule for this rank."""
    text = os.environ.get(ENV_VAR, "").strip()
    if not text:
        return None
    inj = Injector(parse_spec(text), rank)
    return inj if inj.active() else None
