"""Env-driven fault injection for the control plane (docs/fault-tolerance.md).

``HOROVOD_FAULT_SPEC`` (grammar in :mod:`.spec`) describes deterministic
faults — connection drops, stalls, partial writes, corrupted/truncated
frames — injected at named points of the coordinator wire on either side.
The harness exists so the hardening in `runtime/coordinator.py` (reconnect,
replay, heartbeats, CRC frame checks) is provable from tests and
``bench.py --chaos`` rather than only observable in production incidents.

Usage from instrumented code::

    faults = faultinject.for_rank(rank)       # None when no spec is set
    if faults is not None:
        faults.fire("tick")                   # named-point hook
        sock = faults.wrap(sock)              # frame-granular faults

The spec is re-read from the environment on every :func:`for_rank` call, so
tests can monkeypatch ``HOROVOD_FAULT_SPEC`` per scenario; with the variable
unset the layer costs one dict lookup and adds nothing to the hot path.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from .injector import FaultSocket, Injector, Partition
from .spec import FaultRule, parse_spec

__all__ = ["FaultRule", "FaultSocket", "Injector", "Partition", "parse_spec",
           "for_rank", "shared_for_rank", "reset_shared",
           "partition_for_rank"]

ENV_VAR = "HOROVOD_FAULT_SPEC"

# long-lived injectors for callers that re-resolve per event (the integrity
# layer, collective enqueue): hit counters must survive across calls, unlike
# the fresh instance for_rank() hands a controller that keeps its own ref.
# Keyed on (rank, spec text) so a monkeypatched spec starts fresh counters.
_shared: Dict[Tuple[int, str], Injector] = {}
_shared_lock = threading.Lock()


def for_rank(rank: int) -> Optional[Injector]:
    """Build this rank's injector from ``HOROVOD_FAULT_SPEC``; None when the
    spec is unset/empty or matches no rule for this rank."""
    text = os.environ.get(ENV_VAR, "").strip()
    if not text:
        return None
    inj = Injector(parse_spec(text), rank)
    return inj if inj.active() else None


def shared_for_rank(rank: int) -> Optional[Injector]:
    """Like :func:`for_rank` but returns one cached injector per
    (rank, spec) for the process's lifetime, so per-event callers get
    cumulative hit counting. Cleared on ``hvd.shutdown()``."""
    text = os.environ.get(ENV_VAR, "").strip()
    if not text:
        return None
    key = (rank, text)
    with _shared_lock:
        inj = _shared.get(key)
        if inj is None:
            inj = Injector(parse_spec(text), rank)
            _shared[key] = inj
    return inj if inj.active() else None


def partition_for_rank(rank: int) -> Optional[Partition]:
    """This rank's active :class:`Partition` rule, if any — used by KV-side
    callers (the leadership lease) that must observe the cut without owning
    a wrapped socket. Shares the process-cached injector so the partition
    clock matches what the sockets see."""
    inj = shared_for_rank(rank)
    return None if inj is None else inj.partition


def reset_shared() -> None:
    """Drop cached injectors (and their hit counters); a shutdown/re-init
    cycle replays specs from hit 1, mirroring the auto-name counter reset
    in `ops/collective_ops.py`."""
    with _shared_lock:
        _shared.clear()
