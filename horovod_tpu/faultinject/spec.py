"""``HOROVOD_FAULT_SPEC`` grammar (docs/fault-tolerance.md).

A spec is a semicolon-separated list of rules, each of the form::

    kind@point[:arg[:arg2]][#ranks]

* ``kind`` — what to inject:
    - ``conn_drop``  close the control-plane socket (the peer sees a
      connection reset; the worker-side reconnect path takes over)
    - ``delay``      sleep ``arg`` seconds at the point
    - ``corrupt``    flip a byte of the outgoing frame (the receiver's
      CRC32 check rejects it and drops the connection)
    - ``truncate``   send only half the frame, then close the socket
      (the receiver observes a short read mid-frame)
    - ``partial``    split the frame into byte-sized writes (exercises the
      receiver's loop-to-declared-length read path; the frame arrives
      intact)
    - ``nan``        poison the step's gradients with NaN at point
      ``grad`` (drives the HOROVOD_GRAD_GUARD pillar; integrity/gradguard)
    - ``desync``     perturb one parameter leaf on this rank at point
      ``param`` (drives the consistency auditor's detect/heal path)
    - ``hang``       hold this rank's collective submission for ``arg``
      seconds at point ``collective`` — a deterministic wedge; pair with
      HOROVOD_COLLECTIVE_TIMEOUT so the watchdog fires on the peers
    - ``die``        abrupt coordinator death at point ``coordinator``:
      rank 0's server closes its listening socket and every worker
      connection without a BYE, exactly what SIGKILL of rank 0 looks
      like from the workers (drives the standby-failover pillar,
      docs/control-plane.md)
    - ``slow``       coordinator brownout: sleep ``arg`` MILLISECONDS
      inside each negotiation at point ``coordinator`` (the coordinator
      lock is held, so every rank observes the slowdown). At point
      ``rank`` the same sleep fires once per engine tick of the targeted
      rank instead — a chronically slow WORKER rather than a slow
      coordinator (``slow@rank:500#2`` = rank 2 loses 500 ms per step;
      drives the straggler policy, runtime/straggler.py)
    - ``flaky_slow`` like ``slow`` but intermittent: ``arg`` milliseconds,
      fired only on the hits selected by ``arg2`` — a probability in
      (0, 1] applied via a deterministic hash of the per-rank hit index,
      so ``flaky_slow@rank:500:0.3#2`` slows ~30% of rank 2's steps and
      replays IDENTICALLY run to run (no RNG; the straggler policy's
      patience/hysteresis is tested against exactly this flapping)
    - ``partition``  network partition at point ``net``:
      ``partition@net:A|B[:heal_after[:start_after]]`` with ``A``/``B``
      comma-separated rank groups (e.g. ``partition@net:0|1,2:6:2``).
      While active, every control-plane frame crossing the group boundary
      is dropped and the sending socket severed (a cut wire observed as a
      peer reset — silent blackholing would require receive timeouts the
      control plane deliberately does not have), in BOTH directions; the
      FIRST group additionally loses the rendezvous KV (the KV rides with
      the launcher on the second group's side of the cut, so the minority
      coordinator cannot renew its leadership lease —
      docs/fault-tolerance.md). The partition activates ``start_after``
      seconds after process start (default 0) and heals deterministically
      ``heal_after`` seconds later (omitted or 0 = never heals). Clocks
      are per-process monotonic from module import, so co-started ranks
      observe near-identical windows.
* ``point`` — a named injection site. Frame-granular kinds fire inside the
  wrapped socket at point ``frame`` (one hit per sent frame); ``tick``,
  ``exchange``, ``connect`` and ``heartbeat`` are explicit hooks in
  `runtime/coordinator.py`; ``coordinator`` is hit once per negotiation
  inside rank 0's CoordState; ``rank`` once per engine tick
  (`runtime/engine.py`); ``grad`` is hit once per guarded optimizer
  step, ``param`` once per consistency audit, ``collective`` once per
  enqueued collective (`ops/collective_ops.py`).
* ``arg`` — for ``delay`` and ``hang`` the sleep in seconds, for ``slow``
  the sleep in milliseconds, each with an optional second arg restricting
  it to the Nth hit (default: every hit); for ``flaky_slow`` the sleep in
  milliseconds with a REQUIRED second arg, the firing probability. For
  every other kind the 1-based hit index at which the rule fires once
  (default 1).
* ``#ranks`` — optional comma list of ranks the rule applies to
  (default: every rank).

Example (the ISSUE's): ``conn_drop@tick:3;delay@exchange:0.5;corrupt@frame:1``
— drop the connection at the 3rd engine tick, sleep 500 ms before every
exchange, and corrupt the very first control-plane frame sent.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

KINDS = ("conn_drop", "delay", "corrupt", "truncate", "partial",
         "nan", "desync", "hang", "die", "slow", "flaky_slow", "partition")

# kinds applied to outgoing frames by the FaultSocket wrapper (as opposed to
# the named fire() hooks in controller code)
FRAME_KINDS = ("conn_drop", "delay", "corrupt", "truncate", "partial")

# kinds that carry a duration as their first argument
_TIMED_KINDS = ("delay", "hang")

# like _TIMED_KINDS but the argument is in milliseconds (coordinator
# brownouts are naturally sub-second; "slow@coordinator:250" reads better
# than a fractional-seconds form)
_MS_KINDS = ("slow", "flaky_slow")


class FaultRule:
    """One parsed rule; hit counting lives in the Injector."""

    __slots__ = ("kind", "point", "nth", "seconds", "ranks", "prob",
                 "groups", "start")

    def __init__(self, kind: str, point: str, nth: Optional[int],
                 seconds: float, ranks: Optional[Sequence[int]],
                 prob: float = 1.0, groups=None, start: float = 0.0):
        self.kind = kind
        self.point = point
        self.nth = nth            # 1-based hit index; None = every hit
        self.seconds = seconds    # delay/hang sleep; partition heal_after
        self.ranks = None if ranks is None else frozenset(ranks)
        self.prob = prob          # flaky_slow firing probability, else 1.0
        self.groups = groups      # partition only: (frozenset A, frozenset B)
        self.start = start        # partition only: activation delay seconds

    def applies_to(self, rank: int) -> bool:
        return self.ranks is None or rank in self.ranks

    def __repr__(self):
        extra = f":{self.seconds}" if self.kind in _TIMED_KINDS else ""
        if self.kind in _MS_KINDS:
            extra = f":{self.seconds * 1000.0:g}"
        if self.kind == "flaky_slow":
            extra += f":{self.prob:g}"
        if self.kind == "partition":
            a, b = self.groups
            extra = (":" + ",".join(str(r) for r in sorted(a)) + "|" +
                     ",".join(str(r) for r in sorted(b)))
            if self.seconds or self.start:
                extra += f":{self.seconds:g}"
            if self.start:
                extra += f":{self.start:g}"
        nth = f":{self.nth}" if self.nth is not None else ""
        ranks = ("" if self.ranks is None
                 else "#" + ",".join(str(r) for r in sorted(self.ranks)))
        return f"{self.kind}@{self.point}{extra}{nth}{ranks}"


def parse_spec(text: str) -> List[FaultRule]:
    """Parse a ``HOROVOD_FAULT_SPEC`` string; raises ValueError with the
    offending rule on any grammar violation."""
    rules: List[FaultRule] = []
    for raw in (text or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        rule, _, rankpart = raw.partition("#")
        ranks = None
        if rankpart:
            try:
                ranks = [int(r) for r in rankpart.split(",") if r.strip()]
            except ValueError:
                raise ValueError(
                    f"HOROVOD_FAULT_SPEC: bad rank list {rankpart!r} "
                    f"in rule {raw!r}")
        kind, sep, rest = rule.partition("@")
        kind = kind.strip()
        if not sep or kind not in KINDS:
            raise ValueError(
                f"HOROVOD_FAULT_SPEC: bad rule {raw!r} (expected "
                f"kind@point[:arg][#ranks] with kind in {KINDS})")
        parts = rest.split(":")
        point = parts[0].strip()
        if not point:
            raise ValueError(
                f"HOROVOD_FAULT_SPEC: rule {raw!r} names no point")
        args = parts[1:]
        prob = 1.0
        if kind == "partition":
            if point != "net":
                raise ValueError(
                    f"HOROVOD_FAULT_SPEC: partition fires at point 'net', "
                    f"not {point!r} (rule {raw!r})")
            if not args:
                raise ValueError(
                    f"HOROVOD_FAULT_SPEC: partition rule {raw!r} names no "
                    f"rank groups (expected partition@net:A|B)")
            gtext, _, btext = args[0].partition("|")
            try:
                ga = frozenset(int(r) for r in gtext.split(",") if r.strip())
                gb = frozenset(int(r) for r in btext.split(",") if r.strip())
                heal = float(args[1]) if len(args) > 1 else 0.0
                start = float(args[2]) if len(args) > 2 else 0.0
                if not ga or not gb or ga & gb or heal < 0 or start < 0:
                    raise ValueError
            except ValueError:
                raise ValueError(
                    f"HOROVOD_FAULT_SPEC: bad partition rule {raw!r} "
                    f"(expected partition@net:A|B[:heal_after[:start_after]] "
                    f"with disjoint non-empty comma rank groups)")
            rules.append(FaultRule(kind, point, None, heal, ranks,
                                   groups=(ga, gb), start=start))
            continue
        try:
            if kind == "flaky_slow":
                if len(args) < 2:
                    raise ValueError
                seconds = float(args[0]) / 1000.0
                prob = float(args[1])
                if not (0.0 < prob <= 1.0):
                    raise ValueError
                nth = None
            elif kind in _TIMED_KINDS or kind in _MS_KINDS:
                if not args:
                    raise ValueError
                seconds = float(args[0])
                if kind in _MS_KINDS:
                    seconds /= 1000.0
                nth = int(args[1]) if len(args) > 1 else None
            else:
                seconds = 0.0
                nth = int(args[0]) if args else 1
                if nth < 1:
                    raise ValueError
        except ValueError:
            raise ValueError(
                f"HOROVOD_FAULT_SPEC: bad argument(s) {args!r} "
                f"in rule {raw!r}")
        rules.append(FaultRule(kind, point, nth, seconds, ranks, prob))
    return rules
