"""Jepsen-lite history checker for fenced coordinator leadership.

Replays the merged blackbox event logs of a finished (chaos) run and
asserts the two safety properties the lease design promises
(runtime/lease.py, docs/fault-tolerance.md):

* **Single-writer leadership** — at no instant do two coordinators both
  attest that they may serve. Leadership intervals are reconstructed from
  ``K_FENCE`` events: a coordinator holds leadership from its
  ``lease_acquired`` record until its ``self_fenced`` record — or, when it
  never fenced (crashed outright, or the run ended while it led), until
  its LAST successful ``lease_renewed``. The no-fence clip is
  conservative by construction: past the final renewal nothing attests
  leadership, and any REAL overlap inside that tail would have produced
  its own evidence (the deposing acquirer's ``lease_acquired`` plus the
  loser's eventual ``self_fenced`` or rejected frames).
* **Exactly-once step application** — no rank applied the same training
  step twice: the duplicate a zombie coordinator causes by re-running a
  negotiation the new leader also ran. Step logs are supplied by the
  harness (each rank's ordered list of applied step ids); the blackbox
  does not record per-step events.

Timestamps are the flight recorder's wall clock, so the checker is meant
for single-host chaos runs (CI, ``partition@net`` specs) where every rank
shares a clock; cross-host use would need the trace-clock offsets.

The ``split_brain`` doctor signature (blackbox/signatures.py) is a thin
wrapper over :func:`check_history`.
"""

from __future__ import annotations

import itertools
import re
from typing import Dict, Iterable, List, Optional

#: matches the K_FENCE details written by runtime/lease.py
_LEASE_RE = re.compile(
    r"^(lease_acquired|lease_renewed|self_fenced) epoch=(\d+)")

_K_FENCE = "fence"  # literal of blackbox.K_FENCE (no import: keeps this
#                     module cycle-free under blackbox.signatures)


def _iter_events(bundle: Dict[int, dict]):
    for rank in sorted(bundle):
        for ev in bundle[rank].get("events") or []:
            yield rank, ev


def leadership_intervals(bundle: Dict[int, dict]) -> List[dict]:
    """Attested leadership spans, one per (rank, epoch), sorted by start:
    ``{"rank", "epoch", "start", "end", "fenced"}`` with ``fenced`` True
    when the span ended in an explicit ``self_fenced`` record."""
    # (rank, epoch) -> [first_attest_t, last_attest_t, self_fenced_t|None]
    spans: Dict[tuple, list] = {}
    for src, ev in _iter_events(bundle):
        if ev.get("kind") != _K_FENCE:
            continue
        m = _LEASE_RE.match(ev.get("detail") or "")
        if not m:
            continue  # fenced_frame rejections are evidence, not tenure
        what, epoch = m.group(1), int(m.group(2))
        rank = int(ev.get("rank", src))
        t = float(ev.get("t") or 0.0)
        s = spans.setdefault((rank, epoch), [t, t, None])
        if what == "self_fenced":
            s[2] = t if s[2] is None else min(s[2], t)
        else:
            s[0] = min(s[0], t)
            s[1] = max(s[1], t)
    out = []
    for (rank, epoch), (t0, t1, fenced_t) in spans.items():
        end = fenced_t if fenced_t is not None else t1
        out.append({"rank": rank, "epoch": epoch, "start": t0,
                    "end": max(t0, end), "fenced": fenced_t is not None})
    out.sort(key=lambda iv: (iv["start"], iv["epoch"]))
    return out


def fenced_frame_count(bundle: Dict[int, dict]) -> int:
    """How many stamped frames from deposed epochs were rejected anywhere
    in the job — the wire-level evidence that fencing actually bit."""
    n = 0
    for _, ev in _iter_events(bundle):
        if (ev.get("kind") == _K_FENCE
                and (ev.get("detail") or "").startswith("fenced_frame")):
            n += 1
    return n


def check_history(bundle: Dict[int, dict],
                  step_logs: Optional[Dict[int, Iterable]] = None) -> dict:
    """Run every safety check; returns a verdict dict:

    ``single_writer``/``exactly_once`` booleans, the human-readable
    ``violations`` list (empty = clean history), the reconstructed
    ``intervals``, and the job-wide ``fenced_frames`` rejection count."""
    intervals = leadership_intervals(bundle)
    violations: List[str] = []

    # single writer: no two distinct holders' spans may overlap in time
    for a, b in itertools.combinations(intervals, 2):
        if (a["rank"], a["epoch"]) == (b["rank"], b["epoch"]):
            continue
        lo = max(a["start"], b["start"])
        hi = min(a["end"], b["end"])
        if lo < hi:
            violations.append(
                "split-brain: rank %d (epoch %d) and rank %d (epoch %d) "
                "both attested leadership for %.3fs (t=%.3f..%.3f)"
                % (a["rank"], a["epoch"], b["rank"], b["epoch"],
                   hi - lo, lo, hi))

    # an epoch names exactly one holder (the CAS hands it to one winner)
    holder: Dict[int, int] = {}
    for iv in intervals:
        prev = holder.setdefault(iv["epoch"], iv["rank"])
        if prev != iv["rank"]:
            violations.append(
                "epoch %d attested by two holders: rank %d and rank %d"
                % (iv["epoch"], prev, iv["rank"]))

    # epochs only move forward: a later acquisition under a lower epoch
    # means a deposed coordinator re-won leadership it had already lost
    high = 0
    for iv in intervals:
        if iv["epoch"] < high:
            violations.append(
                "epoch regression: rank %d acquired epoch %d at t=%.3f "
                "after epoch %d was already held"
                % (iv["rank"], iv["epoch"], iv["start"], high))
        high = max(high, iv["epoch"])
    single_writer = not violations

    # exactly-once: no step id repeats within one rank's applied log
    step_violations: List[str] = []
    for rank in sorted(step_logs or {}):
        seen = set()
        for step in step_logs[rank]:
            if step in seen:
                step_violations.append(
                    "duplicate apply: rank %s applied step %r twice"
                    % (rank, step))
            seen.add(step)

    return {
        "single_writer": single_writer,
        "exactly_once": not step_violations,
        "violations": violations + step_violations,
        "intervals": intervals,
        "fenced_frames": fenced_frame_count(bundle),
    }


def check_serving_history(bundle: Dict[int, dict],
                          submitted: Iterable,
                          delivered: Iterable) -> dict:
    """Serving-plane verdict for the chaos drills: the leadership checks
    of :func:`check_history` (the serving lease writes the same K_FENCE
    record shapes) plus the request-delivery ledger —

    * **no loss**: every submitted request id appears in ``delivered``;
    * **no duplicates**: no id was delivered (terminally answered at a
      client) more than once — the exactly-once promise the frontend's
      dedupe LRU and the standby's replicated ledger exist to keep.

    ``delivered`` is the concatenated, ordered list of terminal answers
    across every client in the drill (one entry per answered future)."""
    verdict = check_history(bundle)
    submitted = list(submitted)
    delivered = list(delivered)
    counts: Dict[object, int] = {}
    for rid in delivered:
        counts[rid] = counts.get(rid, 0) + 1
    lost = [rid for rid in submitted if rid not in counts]
    dup = [rid for rid, n in counts.items() if n > 1]
    for rid in lost:
        verdict["violations"].append(
            "lost request: %r submitted but never delivered" % (rid,))
    for rid in dup:
        verdict["violations"].append(
            "duplicate delivery: %r answered %d times"
            % (rid, counts[rid]))
    verdict["exactly_once"] = verdict["exactly_once"] and not dup
    verdict["lost"] = len(lost)
    verdict["duplicates"] = len(dup)
    return verdict
