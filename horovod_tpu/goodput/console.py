"""hvdtop — a live console over /metrics + /healthz (docs/goodput.md).

Scrapes the Prometheus endpoint the job already serves
(``HOROVOD_METRICS_PORT``) and renders fleet goodput, the badput stack,
a per-rank state strip, active SLO burn rates, and the anomaly-watch /
liveness state.  Pure-renderer design: ``render(samples, health)`` is a
function from parsed scrape output to a string, so tests and ``--once``
(CI / pipes) share the exact code path the live loop draws with.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

from ..metrics import parse_prometheus

#: display order + one-glyph code for the per-rank strip
STATE_GLYPHS = (("compute", "C"), ("exposed_comm", "x"), ("stall", "S"),
                ("checkpoint", "k"), ("recovery", "R"), ("excluded", "E"),
                ("idle", "."))


def scrape(url, timeout=10):
    """(samples, health) from a running job's endpoint base URL."""
    body = urllib.request.urlopen(url + "/metrics", timeout=timeout) \
        .read().decode()
    samples = parse_prometheus(body)
    try:
        health = json.loads(urllib.request.urlopen(
            url + "/healthz", timeout=timeout).read().decode())
    except Exception:
        health = {}
    return samples, health


def _labeled(samples, name):
    """[(labels_dict, value)] for one sample family."""
    out = []
    for key, value in (samples.get(name) or {}).items():
        out.append((dict(key), value))
    return out


def _bar(frac, width=30):
    n = max(0, min(width, int(round(frac * width))))
    return "#" * n + "-" * (width - n)


def _per_rank_states(samples):
    """rank -> {state: seconds} from the goodput/badput counters."""
    ranks = {}
    for labels, value in _labeled(samples, "hvd_goodput_seconds_total"):
        r = labels.get("rank", "?")
        ranks.setdefault(r, {})["compute"] = \
            ranks.get(r, {}).get("compute", 0.0) + value
    for labels, value in _labeled(samples, "hvd_badput_seconds_total"):
        r = labels.get("rank", "?")
        cause = labels.get("cause", "idle")
        ranks.setdefault(r, {})[cause] = \
            ranks.get(r, {}).get(cause, 0.0) + value
    return ranks


def render(samples, health=None, width=72):
    """One snapshot as plain text.  Raises nothing on partial data — a
    job without the goodput family still renders the liveness header."""
    health = health or {}
    lines = []
    now = time.time()
    up = sum(v for _, v in _labeled(samples, "hvd_up"))
    stamp = max((v for _, v in _labeled(samples,
                                        "hvd_snapshot_unix_seconds")),
                default=None)
    age = (now - stamp) if stamp else None
    head = "hvdtop — up=%s" % (int(up) if up else 0)
    if age is not None:
        head += "  snapshot age %.1fs%s" % (
            age, "  [WEDGED?]" if age > 60 else "")
    status = health.get("status")
    if status:
        head += "  health=%s" % status
    lines.append(head)

    ranks = _per_rank_states(samples)
    total = {s: 0.0 for s, _ in STATE_GLYPHS}
    for states in ranks.values():
        for s, v in states.items():
            total[s] = total.get(s, 0.0) + v
    wall = sum(total.values())
    if wall > 0:
        goodput = total.get("compute", 0.0) / wall
        lines.append("")
        lines.append("fleet goodput %5.1f%%  [%s]  (%.1fs attributed, "
                     "%d ranks)" % (100.0 * goodput, _bar(goodput),
                                    wall, len(ranks)))
        lines.append("badput stack:")
        for state, _ in STATE_GLYPHS:
            if state == "compute":
                continue
            frac = total.get(state, 0.0) / wall
            lines.append("  %-12s %5.1f%%  [%s]  %.2fs"
                         % (state, 100.0 * frac, _bar(frac),
                            total.get(state, 0.0)))
        lines.append("per-rank (dominant state / goodput%):")
        for r in sorted(ranks, key=lambda x: (len(x), x)):
            states = ranks[r]
            rw = sum(states.values())
            dom = max(states, key=states.get) if states else "idle"
            glyph = dict(STATE_GLYPHS).get(dom, "?")
            ratio = states.get("compute", 0.0) / rw if rw > 0 else 0.0
            lines.append("  rank %-4s %s %-12s %5.1f%%  [%s]"
                         % (r, glyph, dom, 100.0 * ratio, _bar(ratio)))
    else:
        lines.append("")
        lines.append("no goodput attribution yet (hvd_goodput_seconds_"
                     "total absent — ledger off or first flush pending)")

    burns = _labeled(samples, "hvd_slo_burn_rate")
    if burns:
        lines.append("SLO burn (fast window; 1.0 = at budget):")
        for labels, value in sorted(burns,
                                    key=lambda kv: kv[0].get("slo", "")):
            mark = "  ALERT" if value >= 2.0 else ""
            lines.append("  %-12s burn %6.2f%s"
                         % (labels.get("slo", "?"), value, mark))

    anomalies = [(labels.get("signal", "?"), v) for labels, v
                 in _labeled(samples, "hvd_anomaly_active") if v > 0]
    if anomalies:
        lines.append("active anomalies: "
                     + ", ".join(sorted(s for s, _ in anomalies)))
    watch = health.get("anomaly_watch") or {}
    for summary in (watch.get("recent") or [])[-4:]:
        lines.append("  recent: %s" % str(summary)[: width - 10])
    slo = watch.get("slo") or {}
    for name in slo.get("alerting") or []:
        lines.append("  slo alerting: %s" % name)
    return "\n".join(lines) + "\n"


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="hvdtop",
        description="live goodput console for a running horovod_tpu job")
    ap.add_argument("--url", default=None,
                    help="endpoint base URL (default http://127.0.0.1:PORT)")
    ap.add_argument("--port", type=int, default=9400,
                    help="metrics port when --url is not given")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh seconds in live mode")
    ap.add_argument("--once", action="store_true",
                    help="render one snapshot and exit (CI / pipes)")
    args = ap.parse_args(argv)
    url = args.url or f"http://127.0.0.1:{args.port}"
    url = url.rstrip("/")
    try:
        while True:
            try:
                samples, health = scrape(url)
            except Exception as exc:
                if args.once:
                    print(f"hvdtop: cannot scrape {url}: {exc}",
                          file=sys.stderr)
                    return 1
                sys.stdout.write(f"\x1b[2J\x1b[Hhvdtop: waiting for {url} "
                                 f"({exc})\n")
                sys.stdout.flush()
                time.sleep(args.interval)
                continue
            text = render(samples, health)
            if args.once:
                sys.stdout.write(text)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + text)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
