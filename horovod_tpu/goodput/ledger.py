"""The goodput ledger: attribute every wall-clock second (docs/goodput.md).

One ledger per process classifies this rank's wall time since attach into
an exhaustive, non-overlapping state set:

* ``compute``      — inside the optimizer update (useful work);
* ``exposed_comm`` — blocked in ``synchronize()`` on a collective that
  completed (communication not hidden behind compute, the PR 6 signal);
* ``stall``        — blocked on a collective that FAILED the enforced
  watchdog, or re-synchronizing elastic state;
* ``checkpoint``   — checkpoint commit stall + shard restore (PR 17);
* ``recovery``     — elastic rebuild after a membership change: restore,
  re-sync, plus the synthetic lost-steps x recent-step-time estimate;
* ``excluded``     — straggler-policy exclusion episodes (PR 12);
* ``idle``         — everything else (computed residually at flush).

Accounting is span-based with nesting: an inner span's time is subtracted
from its enclosing span, so ``synchronize()`` inside the optimizer update
lands in ``exposed_comm``, not ``compute``.  Open spans are sliced at
every flush so the running state is always attributed up to "now" —
which keeps every exported total monotone (they feed counters).

The ledger writes rank-labeled counters in the process registry
(``hvd_goodput_seconds_total{rank}`` / ``hvd_badput_seconds_total{cause,
rank}``) so attribution ships to rank 0 on the existing MSG_METRICS
cadence and merges across ranks for free.  Foreign-rank attributions
(rank 0 observing another rank's exclusion episode) carry that rank's
label but never count toward this process's own wall budget.

Zero-overhead discipline: ``active()`` is a single ``None`` check when
the ledger is off (``HOROVOD_GOODPUT=0``).
"""

from __future__ import annotations

import os
import threading
import time

from ..metrics import instruments

#: The exhaustive state set, in display order.
COMPUTE = "compute"
BADPUT_CAUSES = ("exposed_comm", "stall", "checkpoint", "recovery",
                 "excluded", "idle")
STATES = (COMPUTE,) + BADPUT_CAUSES


class _Span:
    """One open attribution interval on some thread's span stack."""

    __slots__ = ("state", "start", "inner", "sliced", "tid")

    def __init__(self, state, start, tid):
        self.state = state
        self.start = start
        self.inner = 0.0   # wall time covered by already-closed children
        self.sliced = 0.0  # net time already attributed by flush slicing
        self.tid = tid


class _SpanCtx:
    """``with ledger.span("checkpoint"): ...`` convenience wrapper."""

    __slots__ = ("_ledger", "_state", "_span")

    def __init__(self, ledger, state):
        self._ledger = ledger
        self._state = state
        self._span = None

    def __enter__(self):
        self._span = self._ledger.begin(self._state)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        self._ledger.end(self._span)
        return False


class GoodputLedger:
    def __init__(self, rank=0, clock=time.monotonic):
        self._rank = int(rank)
        self._clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        self._acc = {s: 0.0 for s in STATES}      # self wall attribution
        self._foreign = {}                         # (cause, rank) -> secs
        self._ticked = {}                          # counter high-water marks
        self._stacks = {}                          # thread id -> [_Span]
        self._excl_start = {}                      # rank -> episode start
        self._last = {"wall": 0.0, "ratio": 1.0,
                      "states": {s: 0.0 for s in STATES}}
        self._stopped = False

    @property
    def rank(self):
        return self._rank

    def set_rank(self, rank):
        self._rank = int(rank)

    # -- span accounting ---------------------------------------------------
    def begin(self, state):
        if state not in STATES:
            raise ValueError(f"unknown goodput state {state!r}")
        tid = threading.get_ident()
        sp = _Span(state, self._clock(), tid)
        with self._lock:
            self._stacks.setdefault(tid, []).append(sp)
        return sp

    def end(self, span, state=None):
        """Close a span; ``state`` overrides the one it opened with (the
        synchronize() hook decides stall-vs-exposed_comm on the way out)."""
        if span is None:
            return
        if state is not None:
            span.state = state
        now = self._clock()
        with self._lock:
            stack = self._stacks.get(span.tid, [])
            if span in stack:
                # close any children left open by a non-local exit
                while stack and stack[-1] is not span:
                    self._close_locked(stack.pop(), now)
                stack.pop()
                self._close_locked(span, now)
            if not stack:
                self._stacks.pop(span.tid, None)

    def _close_locked(self, span, now):
        dt = now - span.start
        net = max(0.0, dt - span.inner - span.sliced)
        self._acc[span.state] += net
        tid_stack = self._stacks.get(span.tid)
        if tid_stack:
            tid_stack[-1].inner += dt

    def span(self, state):
        return _SpanCtx(self, state)

    # -- direct attribution ------------------------------------------------
    def add(self, cause, seconds, rank=None, synthetic=False):
        """Attribute ``seconds`` to ``cause`` directly.

        ``rank`` other than our own records a foreign-rank observation
        (counter only — never part of this process's wall budget), as does
        ``synthetic=True`` (estimated time, e.g. lost-steps x step-time:
        it overlaps real wall time and must not double-count)."""
        if seconds <= 0:
            return
        with self._lock:
            if rank is not None and int(rank) != self._rank:
                key = (cause, int(rank))
                self._foreign[key] = self._foreign.get(key, 0.0) + seconds
            elif synthetic:
                key = (cause, self._rank)
                self._foreign[key] = self._foreign.get(key, 0.0) + seconds
            else:
                self._acc[cause] += seconds

    def note_excluded(self, rank, excluded):
        """Straggler-policy episode edge (rank 0 observes): start or close
        an exclusion timer for ``rank``; open episodes slice at flush."""
        now = self._clock()
        with self._lock:
            if excluded:
                self._excl_start.setdefault(int(rank), now)
            else:
                start = self._excl_start.pop(int(rank), None)
                if start is not None and now > start:
                    key = ("excluded", int(rank))
                    self._foreign[key] = (self._foreign.get(key, 0.0)
                                          + (now - start))

    # -- flush -------------------------------------------------------------
    def flush(self):
        """Slice open spans, recompute idle, and tick the delta of every
        total into the registry counters.  Called on the engine metrics
        cadence, lazily from ``metrics.local_snapshot()``, and at stop."""
        now = self._clock()
        with self._lock:
            # attribute each thread's RUNNING state up to now
            for stack in self._stacks.values():
                if not stack:
                    continue
                top = stack[-1]
                cur = max(0.0, (now - top.start) - top.inner - top.sliced)
                if cur > 0:
                    self._acc[top.state] += cur
                    top.sliced += cur
            # slice open exclusion episodes
            for rank in list(self._excl_start):
                start = self._excl_start[rank]
                if now > start:
                    key = ("excluded", int(rank))
                    self._foreign[key] = (self._foreign.get(key, 0.0)
                                          + (now - start))
                    self._excl_start[rank] = now
            wall = max(1e-9, now - self._t0)
            attributed = sum(v for s, v in self._acc.items() if s != "idle")
            self._acc["idle"] = max(self._acc["idle"], wall - attributed)
            ratio = min(1.0, self._acc[COMPUTE] / wall)
            self._last = {"wall": wall, "ratio": ratio,
                          "states": dict(self._acc)}
            ticks = []
            me = str(self._rank)
            for state, total in self._acc.items():
                delta = total - self._ticked.get(state, 0.0)
                if delta > 1e-9:
                    ticks.append((state, me, delta))
                    self._ticked[state] = total
            for (cause, rank), total in self._foreign.items():
                key = (cause, int(rank))
                delta = total - self._ticked.get(key, 0.0)
                if delta > 1e-9:
                    ticks.append((cause, str(rank), delta))
                    self._ticked[key] = total
        # registry writes outside our lock (they take their own); touch the
        # families first so scrapes render them before any work happens
        instruments.goodput_seconds().labels(rank=me).inc(0.0)
        instruments.badput_seconds().labels(cause="idle", rank=me).inc(0.0)
        for state, rank, delta in ticks:
            if state == COMPUTE:
                instruments.goodput_seconds().labels(rank=rank).inc(delta)
            else:
                instruments.badput_seconds().labels(
                    cause=state, rank=rank).inc(delta)
        instruments.goodput_ratio().labels(rank=me).set(self._last["ratio"])
        instruments.goodput_wall_seconds().labels(rank=me).set(
            self._last["wall"])
        return self._last

    def summary(self):
        """Last-flushed attribution: ``{"wall", "ratio", "states"}``."""
        return self.flush()

    def stop(self):
        """Final flush; further spans are still accepted (harmless)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self.flush()


# -- process singleton -------------------------------------------------------

_LEDGER = None
_LEDGER_LOCK = threading.Lock()


def enabled():
    return os.environ.get("HOROVOD_GOODPUT", "1").strip().lower() \
        not in ("0", "false", "off")


def active():
    """The attached ledger, or None — the hot-path fast check."""
    return _LEDGER


def attach(rank=0):
    """Create (or update the rank of) the process ledger; None when
    HOROVOD_GOODPUT=0.  Idempotent — the engine calls it at init."""
    global _LEDGER
    if not enabled():
        return None
    with _LEDGER_LOCK:
        if _LEDGER is None:
            _LEDGER = GoodputLedger(rank=rank)
        else:
            _LEDGER.set_rank(rank)
        return _LEDGER


def detach():
    """Final-flush and drop the ledger (shutdown / tests)."""
    global _LEDGER
    with _LEDGER_LOCK:
        led, _LEDGER = _LEDGER, None
    if led is not None:
        led.stop()


def reset_for_tests():
    global _LEDGER
    with _LEDGER_LOCK:
        _LEDGER = None
