"""Declarative SLOs with error budgets and multi-window burn-rate alerts.

Grammar (``HOROVOD_SLO``): a comma-separated list of objectives,

    HOROVOD_SLO=goodput>=0.9,step_p99<=0.5,serving_p99<=0.25

* ``goodput >= R``      — fraction of fleet wall-clock spent computing
  (from the goodput ledger counters); the error budget is ``1 - R``.
* ``step_p99 <= S``     — training-step latency bound in seconds, judged
  per interval from ``hvd_allreduce_latency_seconds`` bucket deltas; the
  budget is the implied 1% of observations allowed over the bound.
* ``serving_p99 <= S``  — same, over ``hvd_serving_request_latency_
  seconds{stage="total"}``.

Each observation interval (the anomaly-watch cadence) produces a
*bad fraction* in [0, 1] per objective — the share of that interval's
budget currency (wall seconds, or step/request count) that violated the
objective.  The burn rate is ``bad_fraction / allowed_fraction``: burning
at exactly 1.0 exhausts the budget precisely at the SLO horizon.  SRE
multi-window evaluation: an alert fires only when BOTH the fast window
(default 6 samples) and the slow window (36 samples) burn above their
thresholds — fast-only spikes and slow-only drifts stay quiet — and
clears when the fast window recovers.  ``hvd_slo_burn_rate{slo}`` always
carries the fast-window burn so dashboards see the pre-alert trend.
"""

from __future__ import annotations

import collections
import logging
import os
import re

from ..metrics import instruments, quantile_from_buckets

logger = logging.getLogger("horovod_tpu.goodput.slo")

#: fast window must burn this much (x budget rate) to fire...
FAST_BURN_THRESHOLD = 2.0
#: ...while the slow window confirms at least budget-rate burn.
SLOW_BURN_THRESHOLD = 1.0
FAST_WINDOW = 6
SLOW_WINDOW = 36
MIN_SAMPLES = 3

_OBJ_RE = re.compile(r"^\s*([a-z0-9_]+)\s*(>=|<=)\s*([0-9.eE+-]+)\s*$")

KNOWN = ("goodput", "step_p99", "serving_p99")


class Objective:
    __slots__ = ("name", "op", "bound", "allowed")

    def __init__(self, name, op, bound):
        self.name = name
        self.op = op
        self.bound = float(bound)
        # the error budget: fraction of the currency allowed to be bad
        if name == "goodput":
            self.allowed = max(1e-9, 1.0 - self.bound)
        else:  # p99 bounds allow 1% of observations over the line
            self.allowed = 0.01

    def __repr__(self):
        return f"{self.name}{self.op}{self.bound:g}"


def parse_slos(spec):
    """Parse the HOROVOD_SLO grammar; unknown or malformed objectives are
    skipped with a warning (an env typo must not kill the job)."""
    out = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        m = _OBJ_RE.match(part)
        if not m or m.group(1) not in KNOWN:
            logger.warning("HOROVOD_SLO: ignoring malformed objective %r "
                           "(known: %s)", part, ", ".join(KNOWN))
            continue
        name, op, bound = m.groups()
        if (name == "goodput") != (op == ">="):
            logger.warning("HOROVOD_SLO: ignoring %r (goodput takes >=, "
                           "latency objectives take <=)", part)
            continue
        out.append(Objective(name, op, bound))
    return out


def _series(snapshot, name):
    return (snapshot.get(name) or {}).get("series") or []


def _counter_total(snapshot, name, causes=None):
    total = 0.0
    for s in _series(snapshot, name):
        if causes is not None and s.get("labels", {}).get("cause") \
                not in causes:
            continue
        total += float(s.get("value", 0.0) or 0.0)
    return total


def _hist_counts(snapshot, name, stage=None):
    """(bounds, summed per-bucket counts) for a histogram family."""
    entry = snapshot.get(name) or {}
    bounds = list(entry.get("buckets") or [])
    counts = []
    for s in entry.get("series") or []:
        if stage is not None and s.get("labels", {}).get("stage") != stage:
            continue
        c = s.get("counts") or []
        if len(c) > len(counts):
            counts += [0] * (len(c) - len(counts))
        for i, v in enumerate(c):
            counts[i] += v
    return bounds, counts


class SLOEngine:
    """Feed me merged snapshots on a fixed cadence; I keep the windows."""

    def __init__(self, objectives, fast_window=FAST_WINDOW,
                 slow_window=SLOW_WINDOW, min_samples=MIN_SAMPLES,
                 fast_burn=FAST_BURN_THRESHOLD,
                 slow_burn=SLOW_BURN_THRESHOLD):
        self.objectives = list(objectives)
        self._fast = int(fast_window)
        self._slow = int(slow_window)
        self._min = int(min_samples)
        self._fast_thresh = float(fast_burn)
        self._slow_thresh = float(slow_burn)
        self._frac = {o.name: collections.deque(maxlen=self._slow)
                      for o in self.objectives}
        self._prev = {}
        self._alerting = {}

    @classmethod
    def from_env(cls, **kw):
        spec = os.environ.get("HOROVOD_SLO", "").strip()
        if not spec:
            return None
        objectives = parse_slos(spec)
        return cls(objectives, **kw) if objectives else None

    # -- per-objective interval bad-fractions ------------------------------
    def _bad_fraction(self, obj, snapshot):
        """The interval's bad share of the objective's currency, or None
        when the interval carried no currency (no wall time / no steps)."""
        if obj.name == "goodput":
            good = _counter_total(snapshot, "hvd_goodput_seconds_total")
            bad = _counter_total(snapshot, "hvd_badput_seconds_total")
            key = ("goodput", "totals")
            pg, pb = self._prev.get(key, (good, bad))
            self._prev[key] = (good, bad)
            dg, db = good - pg, bad - pb
            if dg < 0 or db < 0:  # registry reset
                return None
            if dg + db <= 0:
                return None
            return db / (dg + db)
        family, stage = (("hvd_serving_request_latency_seconds", "total")
                         if obj.name == "serving_p99"
                         else ("hvd_allreduce_latency_seconds", None))
        bounds, counts = _hist_counts(snapshot, family, stage=stage)
        if not bounds or not counts:
            return None
        key = (obj.name, "counts")
        prev = self._prev.get(key)
        self._prev[key] = counts
        if prev is None or len(prev) != len(counts) \
                or sum(counts) < sum(prev):  # first sample / reset
            return None
        delta = [c - p for c, p in zip(counts, prev)]
        total = sum(delta)
        if total <= 0:
            return None
        over = sum(d for i, d in enumerate(delta)
                   if i >= len(bounds) or bounds[i] > obj.bound)
        return over / total

    # -- the cadence entry point -------------------------------------------
    def observe(self, snapshot):
        """Returns a list of edge events:
        ``{"slo", "event": "fire"|"clear", "burn_fast", "burn_slow",
        "bound", "interval_p99"?}``."""
        events = []
        for obj in self.objectives:
            frac = self._bad_fraction(obj, snapshot)
            window = self._frac[obj.name]
            if frac is not None:
                window.append(frac)
            if len(window) < self._min:
                continue
            fast = list(window)[-self._fast:]
            burn_fast = (sum(fast) / len(fast)) / obj.allowed
            burn_slow = (sum(window) / len(window)) / obj.allowed
            instruments.slo_burn_rate().labels(slo=obj.name).set(burn_fast)
            firing = (burn_fast >= self._fast_thresh
                      and burn_slow >= self._slow_thresh)
            was = self._alerting.get(obj.name, False)
            if firing and not was:
                ev = {"slo": obj.name, "event": "fire",
                      "burn_fast": burn_fast, "burn_slow": burn_slow,
                      "op": obj.op, "bound": obj.bound}
                p99 = self._interval_p99(obj, snapshot)
                if p99 is not None:
                    ev["interval_p99"] = p99
                events.append(ev)
                self._alerting[obj.name] = True
            elif was and burn_fast < self._fast_thresh:
                events.append({"slo": obj.name, "event": "clear",
                               "burn_fast": burn_fast,
                               "burn_slow": burn_slow, "op": obj.op,
                               "bound": obj.bound})
                self._alerting[obj.name] = False
        return events

    def _interval_p99(self, obj, snapshot):
        """Evidence only: the latest cumulative p99 estimate via the shared
        bucket-quantile helper."""
        if obj.name == "goodput":
            return None
        family, stage = (("hvd_serving_request_latency_seconds", "total")
                         if obj.name == "serving_p99"
                         else ("hvd_allreduce_latency_seconds", None))
        bounds, counts = _hist_counts(snapshot, family, stage=stage)
        if not bounds or not counts:
            return None
        return quantile_from_buckets(bounds, counts, 0.99)

    def state(self):
        return {"objectives": [repr(o) for o in self.objectives],
                "alerting": sorted(k for k, v in self._alerting.items()
                                   if v)}
