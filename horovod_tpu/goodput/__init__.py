"""Goodput: wall-clock attribution, SLO burn rates, the hvdtop console.

Three pieces (docs/goodput.md):

* :mod:`.ledger` — the per-rank time-attribution ledger classifying every
  wall-clock second into compute / exposed_comm / stall / checkpoint /
  recovery / excluded / idle, exported as rank-labeled counters that ride
  the existing MSG_METRICS shipping and cross-rank merge.
* :mod:`.slo` — declarative objectives (``HOROVOD_SLO``) with error
  budgets and multi-window burn-rate evaluation, run by the anomaly
  watch; burn feeds ``hvd_slo_burn_rate{slo}`` and the hvddoctor
  ``budget_exhausted`` signature.
* :mod:`.console` — ``bin/hvdtop``, the live console over /metrics.
"""

from .ledger import (BADPUT_CAUSES, COMPUTE, STATES, GoodputLedger, active,
                     attach, detach, enabled, reset_for_tests)
from .slo import Objective, SLOEngine, parse_slos

__all__ = [
    "BADPUT_CAUSES", "COMPUTE", "STATES", "GoodputLedger", "active",
    "attach", "detach", "enabled", "reset_for_tests",
    "Objective", "SLOEngine", "parse_slos",
]
