"""ResNet v1.5 family in Flax — the benchmark model.

The reference benches ResNet-50 via ``tf.keras.applications.ResNet50`` in
`examples/tensorflow2_synthetic_benchmark.py:35-40` and torchvision's
``resnet50`` in `examples/pytorch_synthetic_benchmark.py:28-33`. This is a
from-scratch Flax implementation written TPU-first:

  * NHWC layout (TPU-native conv layout), bfloat16 compute / fp32 params by
    default — convs and matmuls land on the MXU at full rate.
  * v1.5 stride placement (stride on the 3x3, not the 1x1) matching
    torchvision/keras defaults, so parameter counts and FLOPs line up with the
    reference benchmarks.
  * BatchNorm stats in fp32; under data parallelism batch stats are per-replica
    (exactly like the reference's per-GPU BN).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        # remat trades HBM traffic for recompute: without it the pre-BN conv
        # outputs are materialised for the backward pass, with it only block
        # boundaries are stored (see jax.checkpoint; useful when HBM-bound).
        block_cls = nn.remat(self.block_cls) if self.remat else self.block_cls
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(self.num_filters * 2 ** i, strides,
                              conv=conv, norm=norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
