"""Inception V3 in Flax — the reference's headline scaling model.

The reference's 90%-at-512-GPUs claim is measured on Inception V3
(`README.rst:74-79`, `docs/benchmarks.rst:13-14`, via
`tf.keras.applications` in the benchmark scripts). TPU-first like the
ResNets: NHWC, bf16 compute / fp32 params, BatchNorm stats in fp32.
Block structure follows the canonical tower layout (stem → 3×InceptionA →
ReductionA → 4×InceptionB → ReductionB → 2×InceptionC), each conv a
conv+BN+ReLU unit.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         param_dtype=jnp.float32)(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(ConvBN, dtype=self.dtype)
        b1 = conv(64, (1, 1))(x, train)
        b5 = conv(48, (1, 1))(x, train)
        b5 = conv(64, (5, 5))(b5, train)
        b3 = conv(64, (1, 1))(x, train)
        b3 = conv(96, (3, 3))(b3, train)
        b3 = conv(96, (3, 3))(b3, train)
        bp = conv(self.pool_features, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class ReductionA(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(ConvBN, dtype=self.dtype)
        b3 = conv(384, (3, 3), (2, 2), padding="VALID")(x, train)
        bd = conv(64, (1, 1))(x, train)
        bd = conv(96, (3, 3))(bd, train)
        bd = conv(96, (3, 3), (2, 2), padding="VALID")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionB(nn.Module):
    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(ConvBN, dtype=self.dtype)
        c = self.channels_7x7
        b1 = conv(192, (1, 1))(x, train)
        b7 = conv(c, (1, 1))(x, train)
        b7 = conv(c, (1, 7))(b7, train)
        b7 = conv(192, (7, 1))(b7, train)
        b77 = conv(c, (1, 1))(x, train)
        b77 = conv(c, (7, 1))(b77, train)
        b77 = conv(c, (1, 7))(b77, train)
        b77 = conv(c, (7, 1))(b77, train)
        b77 = conv(192, (1, 7))(b77, train)
        bp = conv(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b7, b77, bp], axis=-1)


class ReductionB(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(ConvBN, dtype=self.dtype)
        b3 = conv(192, (1, 1))(x, train)
        b3 = conv(320, (3, 3), (2, 2), padding="VALID")(b3, train)
        b7 = conv(192, (1, 1))(x, train)
        b7 = conv(192, (1, 7))(b7, train)
        b7 = conv(192, (7, 1))(b7, train)
        b7 = conv(192, (3, 3), (2, 2), padding="VALID")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionC(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(ConvBN, dtype=self.dtype)
        b1 = conv(320, (1, 1))(x, train)
        b3 = conv(384, (1, 1))(x, train)
        b3a = conv(384, (1, 3))(b3, train)
        b3b = conv(384, (3, 1))(b3, train)
        bd = conv(448, (1, 1))(x, train)
        bd = conv(384, (3, 3))(bd, train)
        bda = conv(384, (1, 3))(bd, train)
        bdb = conv(384, (3, 1))(bd, train)
        bp = conv(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b3a, b3b, bda, bdb, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # stem
        x = conv(32, (3, 3), (2, 2), padding="VALID")(x, train)
        x = conv(32, (3, 3), padding="VALID")(x, train)
        x = conv(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(80, (1, 1), padding="VALID")(x, train)
        x = conv(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # towers
        x = InceptionA(32, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = ReductionA(self.dtype)(x, train)
        x = InceptionB(128, self.dtype)(x, train)
        x = InceptionB(160, self.dtype)(x, train)
        x = InceptionB(160, self.dtype)(x, train)
        x = InceptionB(192, self.dtype)(x, train)
        x = ReductionB(self.dtype)(x, train)
        x = InceptionC(self.dtype)(x, train)
        x = InceptionC(self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
