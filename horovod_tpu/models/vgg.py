"""VGG in Flax — the reference's hard-scaling benchmark model.

VGG-16 is the third model in the reference's scaling table (68% at 512
GPUs, `README.rst:79` — hard because its 138M params make the gradient
allreduce enormous relative to compute). TPU-first: NHWC, bf16 compute /
fp32 params. Configuration "D" (VGG-16) and "E" (VGG-19) layer lists per
the paper; classifier fc widths follow the canonical 4096-4096-classes.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

_CFG = {
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]]
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), use_bias=True, dtype=self.dtype,
                            param_dtype=jnp.float32)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        for width in (4096, 4096):
            x = nn.Dense(width, dtype=self.dtype,
                         param_dtype=jnp.float32)(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


VGG16 = partial(VGG, cfg=_CFG[16])
VGG19 = partial(VGG, cfg=_CFG[19])
