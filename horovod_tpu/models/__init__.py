"""Model zoo: the families the reference benchmarks/examples exercise
(`examples/tensorflow2_synthetic_benchmark.py:35-40`, Keras/torchvision
ResNets) plus the long-context transformer flagship."""

from .resnet import (ResNet, ResNet18, ResNet34, ResNet50, ResNet101,
                     ResNet152)
from .transformer import TransformerLM

__all__ = ["ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
           "ResNet152", "TransformerLM"]
