"""Model zoo: every family the reference's benchmarks/scaling table
exercises — ResNets (`examples/tensorflow2_synthetic_benchmark.py:35-40`),
Inception V3 and VGG-16/19 (the 90%/90%/68% scaling-efficiency trio,
`README.rst:74-79`) — plus the long-context transformer flagship."""

from .inception import InceptionV3
from .resnet import (ResNet, ResNet18, ResNet34, ResNet50, ResNet101,
                     ResNet152)
from .transformer import TransformerLM
from .vgg import VGG, VGG16, VGG19

__all__ = ["InceptionV3", "ResNet", "ResNet18", "ResNet34", "ResNet50",
           "ResNet101", "ResNet152", "TransformerLM", "VGG", "VGG16",
           "VGG19"]
