"""Decoder-only transformer LM in Flax — the long-context flagship model.

No reference counterpart (Horovod 0.18.2 ships CNN benchmark models only,
`examples/tensorflow2_synthetic_benchmark.py:35-40`); the transformer is this
framework's vehicle for its first-class long-context story. TPU-first design:

  * bfloat16 compute / fp32 params; every matmul is MXU-shaped
    (d_model and head_dim multiples of 128/64).
  * Attention is **pluggable**: the default is the Pallas flash kernel
    (`ops/pallas_kernels.flash_attention`, jnp fallback off-TPU); sequence
    parallelism injects ring attention (`parallel/ring_attention.ring_attention`)
    so the SAME model definition trains with the sequence axis sharded over
    an ``sp`` mesh axis (`parallel/sp_training.py`).
  * ``pos_offset`` lets a sequence-sharded caller feed LOCAL token blocks
    while position embeddings stay GLOBAL (offset = shard_index * local_len).
  * Pre-LN blocks, GELU MLP (4x), learned positions, weight-tied output head —
    the standard GPT-2-ish recipe, chosen so parameter counts line up with
    public configs for benchmarking.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

AttnFn = Callable[..., Any]  # (q, k, v) -> out, all [B, T, H, Dh]


def _ln_cls():
    """LayerNorm implementation for the model: XLA's (default) or the
    Pallas :class:`FusedLayerNorm` when ``HVD_FUSED_LN=1`` — see that
    class's docstring for the measured trade-off."""
    return (FusedLayerNorm if os.environ.get("HVD_FUSED_LN") == "1"
            else nn.LayerNorm)


def default_attention(q, k, v):
    """Causal attention via the Pallas flash kernel (falls back to plain jnp
    attention when the kernel is gated off or shapes are ragged)."""
    from ..ops.pallas_kernels import flash_attention

    return flash_attention(q, k, v, causal=True)


def cached_attention(q, k, v, past_mask):
    """Attention for KV-cache inference (serving prefill/decode).

    ``q``: new-token queries [B, T, H, Dh]; ``k``/``v``: cached past K/V
    concatenated with the new block, [B, P+T, H, Dh]; ``past_mask``: bool
    [B, P] validity of each cached slot (False = padding in a gathered
    paged cache). New tokens attend causally within their own block and to
    every valid past slot.

    Masking is exact -inf: a padded slot's softmax weight is exactly 0.0
    and contributes exactly 0.0 to the weighted sum, so — at fixed array
    shapes — a request's output is bit-identical no matter how much
    padding or which other requests share the batch (the property
    ``serving/engine.py``'s batched-equals-sequential guarantee rests on;
    asserted by tests/test_serving.py).
    """
    b, t, _, dh = q.shape
    p = k.shape[1] - t
    scale = 1.0 / float(dh) ** 0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    new_mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]  # [T, T]
    mask = jnp.concatenate(
        [jnp.broadcast_to(past_mask[:, None, :], (b, t, p)),
         jnp.broadcast_to(new_mask[None], (b, t, t))], axis=-1)
    s = jnp.where(mask[:, None], s, -jnp.inf)
    # every row has at least its own (causal-self) slot, so the max is
    # finite and exp(-inf - m) underflows to exactly 0.0 for masked slots
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    probs = e / jnp.sum(e, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))


class FusedLayerNorm(nn.Module):
    """Drop-in ``nn.LayerNorm`` backed by the one-pass Pallas kernels
    (``ops/pallas_kernels.fused_layer_norm``; identical-contract jnp
    fallback off-TPU). Same parameter names/shapes as ``nn.LayerNorm``
    ("scale"/"bias" of [D]), so checkpoints interchange.

    Opt-in (``HVD_FUSED_LN=1``), not the default: measured on a v5e
    GPT-2-medium step the kernels themselves are fast (~1.4 ms/48 norms)
    but the custom-call boundary costs XLA its producer/consumer fusions
    around each norm — end-to-end 38.7k -> 37.3k tok/s. It wins when the
    norm is NOT surrounded by fusible elementwise ops (e.g. inference
    prefill) — hence kept as a knob."""
    epsilon: float = 1e-6
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        from ..ops.pallas_kernels import fused_layer_norm

        d = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (d,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (d,),
                          self.param_dtype)
        return fused_layer_norm(x, scale, bias,
                                eps=self.epsilon).astype(self.dtype)


class Block(nn.Module):
    num_heads: int
    dtype: Any
    attn_fn: AttnFn

    @nn.compact
    def __call__(self, x, kv=None):
        """``kv``: None for training/full-context forward (causal
        ``attn_fn``, returns the block output alone — the seam every
        existing caller uses unchanged), or ``(k_past, v_past, past_mask)``
        for KV-cache inference (``cached_attention`` over past + new,
        returns ``(output, (k_new, v_new))`` so the caller can extend its
        cache)."""
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        dense = partial(nn.Dense, dtype=self.dtype, param_dtype=jnp.float32,
                        kernel_init=nn.initializers.normal(0.02))
        ln = partial(_ln_cls(), dtype=self.dtype, param_dtype=jnp.float32)

        h = ln(name="ln_attn")(x)
        qkv = dense(3 * d_model, name="qkv")(h)
        b, t = qkv.shape[:2]
        # head-major column layout [h][3][hd]: a contiguous shard of the
        # fused kernel's output dim is then WHOLE heads, so tensor
        # parallelism (parallel/tensor.py P(None,"tp") on this kernel)
        # yields head-parallel q/k/v with no resharding — a qkv-major
        # split(3) would cut each tp shard across q/k/v boundaries
        qkv = qkv.reshape(b, t, self.num_heads, 3, head_dim)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        if kv is None:
            out = self.attn_fn(q, k, v)
            new_kv = None
        else:
            k_past, v_past, past_mask = kv
            out = cached_attention(
                q, jnp.concatenate([k_past.astype(k.dtype), k], axis=1),
                jnp.concatenate([v_past.astype(v.dtype), v], axis=1),
                past_mask)
            new_kv = (k, v)
        out = dense(d_model, name="proj")(
            out.astype(self.dtype).reshape(b, t, d_model))
        x = x + out

        h = ln(name="ln_mlp")(x)
        h = dense(4 * d_model, name="mlp_in")(h)
        h = nn.gelu(h)
        h = dense(d_model, name="mlp_out")(h)
        x = x + h
        return x if kv is None else (x, new_kv)


#: rematerialization policies for ``TransformerLM(remat=...)``, mapping mode
#: name -> (wrap_in_remat, jax.checkpoint policy). "full" recomputes
#: everything inside each block during backward (activation memory = one
#: [B,T,D] residual per layer — the lever that lets batch 32+ fit at seq
#: 1024 in 16 GB HBM); "dots" saves matmul outputs and recomputes only
#: elementwise ops (cheaper backward, more memory).
REMAT_POLICIES = {
    "none": (False, None),
    "full": (True, None),
    "dots": (True, jax.checkpoint_policies.dots_with_no_batch_dims_saveable),
}


class TransformerLM(nn.Module):
    vocab_size: int
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[AttnFn] = None  # default: causal flash attention
    remat: str = "none"  # "none" | "full" | "dots" — see REMAT_POLICIES

    @nn.compact
    def __call__(self, tokens, pos_offset=0, return_hidden=False,
                 kv_cache=None):
        """tokens: int [B, T_local]; pos_offset: global position of column 0
        (nonzero when the sequence axis is sharded across devices, and an
        int array broadcastable against [B, T] — e.g. shape [B, 1] — when
        rows sit at different positions, as in batched KV-cache decode).

        ``return_hidden=True`` skips the weight-tied logit head and returns
        the final-LN hidden states [B, T, d_model] — pair with
        ``lm_loss_chunked`` to compute the cross entropy without ever
        materializing the [B, T, vocab] logits (the logits alone are
        batch·seq·vocab·4 bytes; at batch 32, seq 1024, vocab 32k that is
        4.3 GB of HBM the chunked path never allocates).

        ``kv_cache``: None (training / full-context forward, unchanged
        return), or ``(past_k, past_v, past_mask)`` for inference serving —
        ``past_k``/``past_v`` [num_layers, B, P, H, Dh] gathered cache
        (P may be 0 for prefill, padded slots allowed), ``past_mask`` bool
        [B, P] slot validity. Returns ``(logits_or_hidden, (new_k, new_v))``
        with ``new_k``/``new_v`` [num_layers, B, T, H, Dh], the K/V of the
        new tokens for the caller's cache (serving/engine.py writes them
        into its paged pool)."""
        attn = self.attn_fn if self.attn_fn is not None else default_attention
        emb = nn.Embed(self.vocab_size, self.d_model,
                       embedding_init=nn.initializers.normal(0.02),
                       param_dtype=jnp.float32, dtype=self.dtype,
                       name="tok_emb")
        pos_table = self.param(
            "pos_emb", nn.initializers.normal(0.02),
            (self.max_seq_len, self.d_model), jnp.float32)

        # jnp.take clips out-of-range indices, which would silently reuse the
        # last position embedding — fail loudly instead. pos_offset is traced
        # under sequence parallelism (lax.axis_index), so only statically
        # checkable pieces are validated here.
        t = tokens.shape[1]
        # Concrete values (python/numpy ints AND un-traced jax scalars) get
        # the exact offset+t bound; only genuinely traced offsets (sequence
        # parallelism's lax.axis_index) fall through to the local-length
        # check.
        try:
            pos_offset = int(pos_offset)
            concrete = True
        except (TypeError, jax.errors.ConcretizationTypeError,
                jax.errors.TracerIntegerConversionError):
            concrete = False
        if concrete:
            if pos_offset + t > self.max_seq_len:
                raise ValueError(
                    f"sequence [{pos_offset}, {pos_offset + t}) exceeds "
                    f"max_seq_len={self.max_seq_len}")
        elif t > self.max_seq_len:
            raise ValueError(
                f"local sequence length {t} exceeds "
                f"max_seq_len={self.max_seq_len}")
        pos = pos_offset + jnp.arange(t)
        x = emb(tokens) + jnp.take(pos_table, pos, axis=0).astype(self.dtype)
        if self.remat not in REMAT_POLICIES:
            raise ValueError(f"remat={self.remat!r}; expected one of "
                             f"{sorted(REMAT_POLICIES)}")
        use_remat, policy = REMAT_POLICIES[self.remat]
        block_cls = nn.remat(Block, policy=policy) if use_remat else Block
        new_ks, new_vs = [], []
        for i in range(self.num_layers):
            block = block_cls(self.num_heads, self.dtype, attn,
                              name=f"block_{i}")
            if kv_cache is None:
                x = block(x)
            else:
                past_k, past_v, past_mask = kv_cache
                x, (nk, nv) = block(x, (past_k[i], past_v[i], past_mask))
                new_ks.append(nk)
                new_vs.append(nv)
        x = _ln_cls()(dtype=self.dtype, param_dtype=jnp.float32,
                      name="ln_f")(x)
        if return_hidden:
            out = x
        else:
            # weight-tied head: logits = x @ tok_emb.T
            out = emb.attend(x.astype(jnp.float32)).astype(jnp.float32)
        if kv_cache is None:
            return out
        return out, (jnp.stack(new_ks), jnp.stack(new_vs))


def lm_loss(logits, targets):
    """Mean next-token cross entropy; with equal-size shards the global loss
    is the pmean of per-shard values (exact)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def lm_loss_chunked(hidden, emb_table, targets, chunk_tokens=2048,
                    unroll=1):
    """Weight-tied-head cross entropy WITHOUT materializing [B, T, vocab].

    ``hidden``: final hidden states from ``apply(..., return_hidden=True)``;
    ``emb_table``: the token embedding matrix [vocab, d_model] (fp32 param);
    ``targets``: int [B, T]. Tokens are processed ``chunk_tokens`` at a time
    under a rematerialized ``lax.scan``: the forward keeps only the scalar
    partial sums, and the backward recomputes each chunk's logits on the fly
    — peak extra HBM is O(chunk_tokens · vocab) instead of O(B·T·vocab).
    The head matmul runs in bf16 with fp32 accumulation
    (``preferred_element_type``), which is the MXU-native contraction; the
    log-softmax itself stays fp32. Equivalent to
    ``lm_loss(emb.attend(hidden), targets)`` up to bf16 rounding of the
    pre-softmax logits.
    """
    b, t, d = hidden.shape
    total = b * t
    chunk = min(chunk_tokens, total)
    # pad the flattened token stream to a chunk multiple (weight 0 rows), so
    # every (batch, seq) the full-logit path accepts works at full chunk
    # width — a divisor-only fallback can degrade to pathologically thin
    # chunks (e.g. prime token counts)
    pad = (-total) % chunk
    emb_t = emb_table.astype(jnp.bfloat16).T  # [d, vocab]
    h = hidden.astype(jnp.bfloat16).reshape(total, d)
    y = targets.reshape(total)
    w = jnp.ones((total,), jnp.float32)
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        y = jnp.pad(y, (0, pad))
        w = jnp.pad(w, (0, pad))
    n = (total + pad) // chunk
    h, y, w = (h.reshape(n, chunk, d), y.reshape(n, chunk),
               w.reshape(n, chunk))

    @jax.checkpoint
    def body(acc, xs):
        hc, yc, wc = xs
        logits = jnp.dot(hc, emb_t, preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, yc[:, None], axis=-1)[:, 0]
        return acc + jnp.sum(ll * wc), None

    # unroll>1 replicates the body inside the loop so XLA can overlap one
    # chunk's head matmul with the next chunk's operand DMA (the loop
    # boundary is otherwise a scheduling barrier each iteration)
    total_ll, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (h, y, w),
                               unroll=max(1, min(unroll, n)))
    return -total_ll / total


# compact configs for tests / dry runs / benches
TransformerLMTiny = partial(TransformerLM, num_layers=2, num_heads=2,
                            d_model=128, max_seq_len=512)
TransformerLM124M = partial(TransformerLM, num_layers=12, num_heads=12,
                            d_model=768, max_seq_len=2048)
