"""MNIST models for the end-to-end examples.

Parity model: `examples/tensorflow2_mnist.py:25-38` (the conv net used by the
reference's minimal example) — conv(32,3x3) → conv(64,3x3) → maxpool →
dropout → dense(128) → dropout → dense(10), rebuilt in Flax NHWC.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MNISTConvNet(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


class MNISTMLP(nn.Module):
    """Small dense net for fast CPU tests."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)
