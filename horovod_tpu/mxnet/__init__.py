"""MXNet binding surface — `horovod.mxnet` parity on the TPU engine.

Reference parity: `horovod/mxnet/__init__.py` (153 LoC) + `mxnet/mpi_ops.py`
(239 LoC): ``allreduce[_]``, ``allgather``, ``broadcast[_]`` with a
**priority** argument (`mpi_ops.py:52-89`), ``DistributedOptimizer`` rescaling
gradients by 1/size (`__init__.py:40-67`), gluon ``DistributedTrainer``
(:85-105), and ``broadcast_parameters`` (:109-153).

MXNet is NOT part of the TPU image (the project is retired upstream); this
module exists for users porting MXNet scripts from the reference — it
requires an environment with mxnet installed.

**Priority semantics**: the reference pushes ops into MXNet's dependency
engine with a priority that reorders pending submissions
(`mxnet/mpi_ops.cc:132-200`). There is no dependency engine here; instead a
:func:`deferred_execution` window provides the async-handle layer — inside
it, the in-place ops (``allreduce_``/``broadcast_``) queue instead of
executing, and on exit every queued op is SUBMITTED to the engine in
(-priority, call-order) order, then synchronized and written back. The gluon
``DistributedTrainer`` wraps its gradient pass in this window, so
``priority`` genuinely reorders engine submission exactly where the
reference uses it. Outside a window (and for out-of-place ops, whose return
value is needed immediately) execution is inline and ``priority`` is a
no-op — recorded as a disposition in docs/design.md.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import numpy as np

from .. import basics
from ..basics import (  # noqa: F401  (re-exported API surface; probe set
    # mirrors reference mxnet/__init__.py via mxnet/mpi_ops.py)
    Adasum,
    Average,
    Sum,
    ddl_built,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mlsl_built,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    shutdown,
    size,
)
from ..ops import collective_ops as _ops

try:
    import mxnet as mx

    _HAVE_MX = True
except ImportError:  # pragma: no cover - exercised only without mxnet
    mx = None
    _HAVE_MX = False


def _require_mx():
    if not _HAVE_MX:
        raise ImportError(
            "horovod_tpu.mxnet requires the 'mxnet' package, which is not "
            "installed (the MXNet project is retired). The TPU-native "
            "training surface is JAX (horovod_tpu / horovod_tpu.spmd).")
    return mx


def _to_numpy(tensor) -> np.ndarray:
    _require_mx()
    return tensor.asnumpy() if hasattr(tensor, "asnumpy") \
        else np.asarray(tensor)


def _from_result(result, like):
    m = _require_mx()
    return m.nd.array(np.asarray(result), dtype=like.dtype)


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              priority: int = 0):
    op = Average if average else Sum
    return _from_result(
        _ops.synchronize(_ops.allreduce_async(_to_numpy(tensor), name=name,
                                              op=op)), tensor)


# ---------------------------------------------------------- deferral window
# thread-local stack of pending (priority, seq, submit, writeback) entries;
# see the module docstring for the semantics
_defer_local = threading.local()


def _defer_queue():
    return getattr(_defer_local, "queue", None)


@contextlib.contextmanager
def deferred_execution():
    """Async-handle window: in-place collectives called inside queue, and on
    exit are submitted in (-priority, call-order) order — the TPU analogue
    of the reference handing ops to MXNet's dependency engine with a
    priority (`mxnet/mpi_ops.cc:132-200`). All ranks must order identically,
    which holds because priorities derive from shared structure (parameter
    indices) on every rank."""
    if _defer_queue() is not None:
        raise RuntimeError("deferred_execution windows do not nest")
    _defer_local.queue = []
    try:
        yield
        queue, _defer_local.queue = _defer_local.queue, None
        order = sorted(range(len(queue)),
                       key=lambda k: (-queue[k][0], queue[k][1]))
        handles = []
        try:
            for k in order:  # submit by priority
                handles.append((k, queue[k][2]()))
            for k, h in handles:
                queue[k][3](_ops.synchronize(h))
        except Exception:
            # drain whatever is already in flight so a transient error does
            # not orphan named ops (which would collide as duplicates or
            # stall peers on the NEXT step), then surface the original
            for k, h in handles:
                try:
                    _ops.synchronize(h)
                except Exception:
                    pass
            raise
    finally:
        _defer_local.queue = None


def _enqueue_deferred(queue, priority, tensor, submit):
    """Queue one in-place op: snapshot the input now (the engine sees the
    value at call time, like the reference's engine push), write back on
    synchronize."""

    def writeback(result):
        tensor[:] = _from_result(result, tensor)

    queue.append((priority, len(queue), submit, writeback))
    return tensor


def allreduce_(tensor, average: bool = True, name: Optional[str] = None,
               priority: int = 0):
    queue = _defer_queue()
    if queue is not None:
        op = Average if average else Sum
        arr = _to_numpy(tensor)
        return _enqueue_deferred(
            queue, priority, tensor,
            lambda: _ops.allreduce_async(arr, name=name, op=op))
    out = allreduce(tensor, average=average, name=name, priority=priority)
    tensor[:] = out
    return tensor


def allgather(tensor, name: Optional[str] = None, priority: int = 0):
    return _from_result(
        _ops.synchronize(_ops.allgather_async(_to_numpy(tensor), name=name)),
        tensor)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              priority: int = 0):
    return _from_result(
        _ops.synchronize(_ops.broadcast_async(_to_numpy(tensor), root_rank,
                                              name=name)), tensor)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             priority: int = 0):
    """Alltoall; with ``splits`` (length-world, summing to dim 0) the
    ragged alltoallv form, returning ``(output, received_splits)``
    (later-horovod's API shape — received_splits[src] counts the output
    rows that came from rank ``src``). Out-of-place, like ``allgather``
    (the output shape differs from the input's) — out-of-place ops always
    execute inline (module docstring), so ``priority`` is accepted purely
    for surface symmetry and never reorders anything."""
    res = _ops.synchronize(_ops.alltoall_async(_to_numpy(tensor),
                                               splits=splits, name=name))
    from ..runtime.messages import AlltoallvResult

    if isinstance(res, AlltoallvResult):
        m = _require_mx()
        return (_from_result(res.output, tensor),
                m.nd.array(np.asarray(res.received_splits), dtype="int32"))
    return _from_result(res, tensor)


def broadcast_(tensor, root_rank: int = 0, name: Optional[str] = None,
               priority: int = 0):
    queue = _defer_queue()
    if queue is not None:
        arr = _to_numpy(tensor)
        return _enqueue_deferred(
            queue, priority, tensor,
            lambda: _ops.broadcast_async(arr, root_rank, name=name))
    out = broadcast(tensor, root_rank=root_rank, name=name, priority=priority)
    tensor[:] = out
    return tensor


class DistributedOptimizer:
    """Wraps an mxnet optimizer: allreduce-SUM each gradient then rescale by
    1/size before update (`mxnet/__init__.py:40-67`)."""

    def __init__(self, optimizer):
        _require_mx()
        self._opt = optimizer

    def update(self, index, weight, grad, state):
        g = allreduce(grad, average=False, name=f"grad.{index}",
                      priority=-index)
        g = g / basics.size()
        return self._opt.update(index, weight, g, state)

    def update_multi_precision(self, index, weight, grad, state):
        g = allreduce(grad, average=False, name=f"grad.{index}",
                      priority=-index)
        g = g / basics.size()
        return self._opt.update_multi_precision(index, weight, g, state)

    def __getattr__(self, item):
        return getattr(self._opt, item)


def DistributedTrainer(params, optimizer, optimizer_params=None):
    """gluon Trainer whose ``_allreduce_grads`` goes through the engine
    (`mxnet/__init__.py:85-105`)."""
    m = _require_mx()
    from mxnet import gluon

    class _Trainer(gluon.Trainer):
        def _allreduce_grads(self):
            # the deferral window submits every gradient in priority order
            # (the reference's dependency-engine priority, mpi_ops.py:52-89)
            # before synchronizing any of them — all collectives overlap in
            # the engine instead of running strictly one at a time
            with deferred_execution():
                for i, param in enumerate(self._params):
                    if param.grad_req != "null":
                        # per-context suffix: all grads are now in flight
                        # CONCURRENTLY, and the engine rejects duplicate
                        # in-flight names
                        for j, g in enumerate(param.list_grad()):
                            allreduce_(g, average=True, name=f"grad.{i}.{j}",
                                       priority=-i)

    scaled = dict(optimizer_params or {})
    return _Trainer(params, optimizer, scaled)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast a gluon ParameterDict / dict of NDArrays
    (`mxnet/__init__.py:109-153`); deferred-init parameters are skipped (the
    reference attaches a hook; porting scripts should initialize first).

    Only ``DeferredInitializationError`` is skipped: any other per-parameter
    error must fail loudly — silently skipping on a subset of ranks would
    desynchronize the collective schedule (ranks pairing broadcasts of
    *different* parameters under the same names).
    """
    mx = _require_mx()
    deferred = getattr(getattr(mx, "gluon", None), "parameter", None)
    deferred = getattr(deferred, "DeferredInitializationError", None)
    skip_types = (deferred,) if deferred is not None else ()
    if hasattr(params, "items"):
        items = sorted(params.items())
    else:
        items = list(enumerate(params))
    for name, p in items:
        try:
            data = p.data() if hasattr(p, "data") and callable(p.data) else p
        except skip_types:
            continue  # deferred init — nothing to broadcast yet
        broadcast_(data, root_rank=root_rank, name=f"bp.{name}")
