"""XLA execution of negotiated responses.

This is the TPU-native replacement for the reference's op implementations
(`horovod/common/ops/{mpi,nccl,gloo}_operations.cc` + the fusion-buffer memcpys in
`collective_operations.cc`). Where NCCL ops memcpy entries into a fusion buffer,
launch ``ncclAllReduce`` on a dedicated stream, and memcpy out
(`nccl_operations.cc:55-105`), here each rank's entries are packed (on-device
concat) into a 1-D buffer, the per-rank buffers form ONE global ``jax.Array``
sharded over the rank mesh, and a cached compiled XLA program performs the
collective — GSPMD inserts the actual ICI/DCN allreduce/allgather. Packing,
reduction, scaling, and averaging all fuse into a single compiled program, the
XLA analogue of horovod's fused-buffer + NCCL-kernel pipeline.

Compiled programs are cached per (op, world, buffer length, dtype, scale)
signature — the analogue of the reference's ResponseCache
(`response_cache.{h,cc}`) fast path: steady-state training hits the cache and
skips all compilation/negotiation overhead.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.env import env_on as _env_on
from .messages import (AlltoallvResult, RequestType, Response, ResponseType,
                       TensorTableEntry)

MESH_AXIS = "hvd"


def _np_dtype(x) -> str:
    return str(x.dtype)


class Executor:
    """Executes one Response across all local ranks' pending entries."""

    def __init__(self, state):
        import jax

        self._jax = jax
        self._state = state
        # eager collectives run over the *rank* mesh: one device per rank
        # (the LOCAL/CROSS analogue of mpi_context.cc:150-158 lives in how the
        # launcher lays ranks onto hosts; ICI within a host, DCN across).
        self._mesh = state.rank_mesh
        self._rank_devices = list(state.rank_devices)
        self._world = state.size
        pid = jax.process_index()
        self._local_ranks = [r for r, d in enumerate(self._rank_devices)
                             if d.process_index == pid]
        # multiprocess: only this process's entries are visible; shapes/
        # dtypes of remote contributions come from the negotiated Response
        # metadata (coordinator.py), letting joined ranks execute collectives
        # they have no local entries for
        self._multiproc = state.mode == "multiprocess"
        self._self_rank = state.rank0
        # compiled-collective cache (ResponseCache analogue)
        self._fn_cache: Dict[Tuple, Any] = {}
        # two-level ("dcn","ici") factorization of the rank mesh: ranks on
        # one host form an ici row (reference LOCAL communicator), one row
        # per host (CROSS). Opt-in per op via the reference's env knobs
        # HOROVOD_HIERARCHICAL_ALLREDUCE / _ALLGATHER
        # (operations.cc:433-443); HVD_LOCAL_SIZE overrides the grouping for
        # single-host topologies (tests, virtual-device CI).
        self._mesh2 = self._build_two_level_mesh(state)
        self._hier_allreduce = (self._mesh2 is not None
                                and _env_on("HOROVOD_HIERARCHICAL_ALLREDUCE"))
        self._hier_allgather = (self._mesh2 is not None
                                and _env_on("HOROVOD_HIERARCHICAL_ALLGATHER"))
        # wire accounting for the most recent allreduce (benchmark/telemetry
        # surface): mode actually used ("" = full-precision) and the bytes
        # the compiled program moved per reduce+gather round
        self.last_wire_mode: str = ""
        self.last_wire_bytes: int = 0
        # collective algorithm the most recent allreduce rode ("ring" =
        # GSPMD psum or the flat ring; "tree"/"hier" = zoo members)
        self.last_algorithm: str = "ring"

    def _build_two_level_mesh(self, state):
        from jax.sharding import Mesh

        if self._multiproc:
            # multi-controller: every process must compile the IDENTICAL
            # program for a negotiated collective, so the grouping may only
            # come from a env fact the launcher exports identically to all
            # ranks — per-host local_size can differ across heterogeneous
            # hosts and would silently split the job onto two programs
            ls = int(os.environ.get("HVD_UNIFORM_LOCAL_SIZE", 0))
        else:
            # single process (cluster/standalone): any grouping is trivially
            # uniform; HVD_LOCAL_SIZE overrides for virtual-topology tests
            ls = int(os.environ.get("HVD_LOCAL_SIZE", 0)) or state.local_size
        if ls <= 1 or ls >= self._world or self._world % ls != 0:
            return None
        # rank numbering is host-major (launcher assigns local ranks
        # contiguously): rank = cross_rank * local_size + local_rank
        rows = np.asarray(self._rank_devices, dtype=object).reshape(
            self._world // ls, ls)
        return Mesh(rows, ("dcn", "ici"))

    # ------------------------------------------------------------------ pack
    def _pack(self, entries: Sequence[TensorTableEntry], pad_to: int = 0):
        """Concat one rank's entries into a flat buffer on that rank's device.

        Analogue of MemcpyInFusionBuffer (`collective_operations.cc:~40-100`).
        """
        import jax.numpy as jnp

        parts = [jnp.ravel(e.array) for e in entries]
        buf = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        if pad_to and buf.shape[0] < pad_to:
            buf = jnp.pad(buf, (0, pad_to - buf.shape[0]))
        return buf

    def _global_array(self, bufs: List[Any], length: int,
                      sharding: Optional[Any] = None):
        """Stack per-rank buffers into a (world, L) array sharded over the mesh."""
        jax = self._jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if sharding is None:
            sharding = NamedSharding(self._mesh, P(MESH_AXIS))
        shards = [b.reshape(1, length) for b in bufs]
        return jax.make_array_from_single_device_arrays(
            (self._world, length), sharding, shards
        )

    def _row_sharding2(self):
        """Row-per-rank sharding expressed over the two-level mesh (same
        device order as the flat rank mesh, so shards place identically)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self._mesh2, P(("dcn", "ici")))

    def _shard_by_rank(self, out) -> Dict[int, Any]:
        dev_to_rank = {d: r for r, d in enumerate(self._rank_devices)}
        res = {}
        for s in out.addressable_shards:
            r = dev_to_rank.get(s.device)
            if r is not None:
                res[r] = s.data
        return res

    # -------------------------------------------------------- compiled kernels
    def _allreduce_fn(self, n: int, length: int, dtype: str, average: bool,
                      prescale: float, postscale: float):
        key = ("allreduce", n, length, dtype, average, prescale, postscale)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(self._mesh, P(MESH_AXIS))
            size = self._world
            isint = np.issubdtype(np.dtype(dtype), np.integer)

            def kernel(g):
                x = g
                if prescale != 1.0:
                    x = x * np.asarray(prescale, g.dtype)
                s = jnp.sum(x, axis=0, keepdims=True)  # GSPMD -> allreduce
                if average:
                    s = s // size if isint else s / np.asarray(size, s.dtype)
                if postscale != 1.0:
                    s = s * np.asarray(postscale, s.dtype)
                return jnp.broadcast_to(s, (n, length))

            fn = jax.jit(kernel, out_shardings=sharding)
            self._fn_cache[key] = fn
        return fn

    def _allreduce2_fn(self, n: int, length: int, dtype: str, average: bool,
                       prescale: float, postscale: float):
        """Two-level allreduce over the ("dcn","ici") rank mesh:
        reduce_scatter ICI → allreduce DCN → all_gather ICI, the
        NCCLHierarchicalAllreduce decomposition (`nccl_operations.cc:150-346`)
        expressed with explicit XLA collectives under shard_map."""
        key = ("allreduce2", n, length, dtype, average, prescale, postscale)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P

            mesh = self._mesh2
            ici = mesh.shape["ici"]
            size = self._world
            isint = np.issubdtype(np.dtype(dtype), np.integer)
            pad = (-length) % ici

            def body(row):  # [1, L]: this rank's contribution
                x = row[0]
                if prescale != 1.0:
                    x = x * np.asarray(prescale, x.dtype)
                if pad:
                    x = jnp.pad(x, (0, pad))
                s = lax.psum_scatter(x, "ici", scatter_dimension=0,
                                     tiled=True)
                s = lax.psum(s, "dcn")
                out = lax.all_gather(s, "ici", tiled=True)
                if pad:
                    out = out[:length]
                if average:
                    out = (out // size if isint
                           else out / np.asarray(size, out.dtype))
                if postscale != 1.0:
                    out = out * np.asarray(postscale, out.dtype)
                return out[None]

            sm = jax.shard_map(body, mesh=mesh,
                               in_specs=P(("dcn", "ici")),
                               out_specs=P(("dcn", "ici")),
                               check_vma=False)
            fn = jax.jit(sm)
            self._fn_cache[key] = fn
        return fn

    def _algo_choice(self) -> str:
        """Coordinator-plane collective algorithm selection: an explicit
        ``HOROVOD_GSPMD_ALGO=ring|tree|hier`` wins; unset or ``auto``
        follows the joint tuner's broadcast
        (`ops/adaptive.set_autotuned_algorithm`, the fourth tuned
        ``ResponseList`` field) and stays ``ring`` — the untouched dispatch
        — until one arrives."""
        from .. import spmd as _spmd
        from ..ops import adaptive as _adaptive

        v = _spmd.gspmd_algo()  # validates the env value
        if os.environ.get("HOROVOD_GSPMD_ALGO", "").strip().lower() in (
                "ring", "tree", "hier"):
            return v
        return _adaptive.autotuned_algorithm() or "ring"

    def _allreduce_tree_fn(self, n: int, length: int, dtype: str,
                           average: bool, prescale: float, postscale: float):
        """Recursive-halving/doubling allreduce over the rank mesh
        (`spmd.quantized_allreduce_tree` on the exact wire): O(log n)
        latency rounds instead of the ring's n-1 — the zoo member the
        tuner picks for small payloads."""
        key = ("allreduce_tree", n, length, dtype, average, prescale,
               postscale)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .. import spmd as _spmd
            from ..basics import Sum

            mesh = self._mesh
            size = self._world

            def body(row):  # [1, L]: this rank's contribution
                x = row[0]
                if prescale != 1.0:
                    x = x * np.asarray(prescale, x.dtype)
                out = _spmd.quantized_allreduce_tree(x, Sum, MESH_AXIS,
                                                     wire="off")
                if average:
                    out = out / np.asarray(size, out.dtype)
                if postscale != 1.0:
                    out = out * np.asarray(postscale, out.dtype)
                return out.astype(x.dtype)[None]

            sm = _spmd._shard_map(body, mesh, in_specs=P(MESH_AXIS),
                                  out_specs=P(MESH_AXIS))
            fn = jax.jit(sm, out_shardings=NamedSharding(mesh,
                                                         P(MESH_AXIS)))
            self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------- quantized wire path
    @staticmethod
    def quantized_wire_layout(length: int, world: int,
                              block: Optional[int] = None,
                              bits: int = 8) -> Dict[str, int]:
        """Byte accounting of the quantized wire program for a fused bucket
        of ``length`` fp32 elements over ``world`` ranks: each rank's row is
        padded to ``world`` chunks of whole quantization blocks, the
        all-to-all moves integer payload + f32 scales, and the all-gather
        moves the same for the requantized reduction. ``bits`` selects the
        grid: int8 is 1 byte/element, int4 packs two values per byte.
        ``wire_bytes`` is the per-rank total for one reduce+gather round
        (the number the ≤28%% acceptance test counts)."""
        from ..ops import compression as comp

        block = block or comp.block_size()
        chunk = -(-length // world)
        chunk = -(-chunk // block) * block
        padded = chunk * world
        if bits == 4:
            payload = padded // 2             # int4: two values per byte
        else:
            payload = padded                  # int8: 1 byte/element
        scales = (padded // block) * 4        # one f32 scale per block
        return {"block": block, "chunk": chunk, "padded": padded,
                "bits": bits,
                "payload_bytes": payload, "scale_bytes": scales,
                "wire_bytes": 2 * (payload + scales)}

    def _effective_wire(self, response, entries_by_rank, dtype: str,
                        length: int, adasum: bool) -> str:
        """The wire mode this bucket actually uses. The negotiated
        ``Response.compression`` wins (coordinated planes put it there so
        every rank compiles the same program); the native controller's tick
        frame cannot carry it, so that plane quantizes only when every local
        entry in the bucket requested the same mode. The bypass rules below
        depend only on negotiated facts (dtype, length) so they resolve
        identically on every rank."""
        wire = getattr(response, "compression", "")
        if not wire:
            # same tensor, different modes across ranks = a config error
            # (HOROVOD_COMPRESSION must be uniform) — fail fast, exactly
            # like the coordinated planes' validation does. Distinct
            # TENSORS with different modes inside one native-fused bucket
            # are legitimate (the tick frame's fusion sig predates the
            # field) and downgrade to the exact wire below.
            by_name: Dict[str, set] = {}
            for es in entries_by_rank.values():
                for e in es or ():
                    by_name.setdefault(e.tensor_name, set()).add(
                        e.compression)
            for tname, modes in by_name.items():
                if len(modes) > 1 and not all(
                        m.startswith("adaptive:") for m in modes):
                    # all-adaptive mismatches are a decision boundary
                    # racing the enqueue, resolved below; anything else
                    # is a config error
                    raise ValueError(
                        f"Mismatched compression for tensor '{tname}': "
                        f"ranks requested {sorted(m or 'none' for m in modes)}"
                        " (set HOROVOD_COMPRESSION identically on every "
                        "rank)")
            wires = {e.compression
                     for es in entries_by_rank.values() if es for e in es}
            if len(wires) > 1 and all(
                    w.startswith("adaptive:") for w in wires):
                # a bitwidth-decision boundary can race a native tick so
                # ranks transiently request different adaptive grids —
                # resolve to the least aggressive one, like the
                # coordinated planes' negotiation does
                order = {"adaptive:int4": 0, "adaptive:int8": 1,
                         "adaptive:bf16": 2}
                wire = max(wires, key=lambda w: order.get(w, 2))
            else:
                wire = wires.pop() if len(wires) == 1 else ""
        if wire.startswith("adaptive:"):
            # the negotiated per-bucket bitwidth decision: the concrete
            # grid after the prefix is what compiles
            wire = wire.split(":", 1)[1]
        if wire not in ("int8", "int8-dcn", "int4", "bf16"):
            return ""
        if adasum or self._world == 1:
            return ""
        if not np.issubdtype(np.dtype(dtype), np.floating):
            return ""  # integer/bool tensors ride the exact wire
        floor = int(os.environ.get("HOROVOD_COMPRESSION_MIN_SIZE", 1024))
        if length < floor:
            return ""  # small buckets: scale overhead beats the savings
        if wire == "int4":
            from ..ops import compression as comp
            if comp.block_size() % 2:
                return "int8"  # nibble packing needs an even block
        return wire

    def _allreduce_q_fn(self, n: int, length: int, dtype: str, average: bool,
                        prescale: float, postscale: float, wire: str):
        """Block-quantized allreduce as ONE compiled program (the EQuARX
        wire format, PAPERS.md arXiv:2506.17615): quantize → all_to_all of
        int8 payload + f32 scales (the reduce-scatter hop) → dequantize,
        sum in f32, requantize → all_gather → dequantize. Per-rank scales
        don't commute with the sum, so the reduction must
        dequant-sum-requant — which is why this lives in the executor's
        compiled program and not in the framework-level Compressor.

        ``int8-dcn`` runs the mixed hierarchical form over the
        ("dcn","ici") mesh: ICI hops ride bf16 (fast wire, cheap cast) and
        only the slow DCN hop pays the quantization — EQuARX's insight
        applied to the NCCLHierarchical decomposition of _allreduce2_fn.
        Without a two-level topology it degrades to the flat int8 program.

        ``int4`` is the same program on the 4-bit grid and ALWAYS rides
        the packed wire — nibble packing (two values per byte + 4 scale
        bytes per block row) IS its wire format; there is no unpacked
        int4 layout.
        """
        from ..ops import compression as comp
        from ..ops import pallas_kernels as pk

        block = comp.block_size()
        bits = 4 if wire == "int4" else 8
        # HOROVOD_PACKED_WIRE: single-buffer wire rows [int8 payload |
        # 4 scale bytes] assembled by the fused quantize+pack kernel — ONE
        # all_to_all and ONE all_gather instead of two of each, and no
        # separate scale-quantize pass. Bit-identical values (same
        # quantize formula, same f32 sum order); same wire_bytes total.
        packed = bits == 4 or os.environ.get(
            "HOROVOD_PACKED_WIRE", "").lower() in ("1", "on", "true")
        hier = wire == "int8-dcn" and self._mesh2 is not None
        key = ("allreduce_q",
               "int8-dcn" if hier else ("int4" if bits == 4 else "int8"),
               n, length, dtype, average, prescale, postscale, block, packed)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P

            size = self._world

            def q_hop(x, axis, m):
                # quantized allreduce of flat f32 ``x`` over mesh axis
                # ``axis`` (m participants); both collectives move int8
                # payload + per-block f32 scales
                ln = x.shape[0]
                chunk = -(-ln // m)
                chunk = -(-chunk // block) * block
                padded = chunk * m
                if padded != ln:
                    x = jnp.pad(x, (0, padded - ln))
                if packed:
                    nb = chunk // block
                    if bits == 4:
                        quant_pack = pk.int4_quantize_pack
                        unpack = pk.int4_unpack
                        prow = block // 2 + pk.PACK_SCALE_BYTES
                    else:
                        quant_pack = pk.int8_quantize_pack
                        unpack = pk.int8_unpack
                        prow = block + pk.PACK_SCALE_BYTES
                    p = quant_pack(x.reshape(padded // block, block))
                    wt = lax.all_to_all(p.reshape(m, nb * prow), axis, 0, 0,
                                        tiled=True)
                    q2, s2 = unpack(wt.reshape(m * nb, prow))
                    d = (q2.astype(jnp.float32).reshape(m, nb, block)
                         * s2.reshape(m, nb, 1))
                    red = jnp.sum(d.reshape(m, chunk), axis=0)
                    rp = quant_pack(red.reshape(nb, block))
                    gp = lax.all_gather(rp.reshape(nb * prow), axis,
                                        tiled=True)
                    rq, rs = unpack(gp.reshape(m * nb, prow))
                    out = (rq.astype(jnp.float32) * rs).reshape(padded)
                    return out[:ln] if padded != ln else out
                q, s = comp.quantize_blocks(x, block)
                qt = lax.all_to_all(q.reshape(m, chunk), axis, 0, 0,
                                    tiled=True)
                st = lax.all_to_all(s.reshape(m, chunk // block), axis, 0, 0,
                                    tiled=True)
                d = (qt.reshape(m, chunk // block, block).astype(jnp.float32)
                     * st[..., None])
                red = jnp.sum(d.reshape(m, chunk), axis=0)
                rq, rs = comp.quantize_blocks(red, block)
                out = comp.dequantize_blocks(
                    lax.all_gather(rq, axis, tiled=True),
                    lax.all_gather(rs, axis, tiled=True), block=block)
                return out[:ln] if padded != ln else out

            if hier:
                mesh = self._mesh2
                ici = mesh.shape["ici"]
                ndcn = mesh.shape["dcn"]
                pad_i = (-length) % ici

                def body(row):  # [1, L]: this rank's contribution
                    x = row[0]
                    if prescale != 1.0:
                        x = x * np.asarray(prescale, x.dtype)
                    x = x.astype(jnp.bfloat16)  # ICI wire format
                    if pad_i:
                        x = jnp.pad(x, (0, pad_i))
                    s = lax.psum_scatter(x, "ici", scatter_dimension=0,
                                         tiled=True)
                    if ndcn > 1:
                        red = q_hop(s.astype(jnp.float32), "dcn", ndcn)
                    else:
                        red = s.astype(jnp.float32)
                    out = lax.all_gather(red.astype(jnp.bfloat16), "ici",
                                         tiled=True).astype(jnp.float32)
                    if pad_i:
                        out = out[:length]
                    if average:
                        out = out / np.float32(size)
                    if postscale != 1.0:
                        out = out * np.float32(postscale)
                    return out.astype(dtype)[None]

                sm = jax.shard_map(body, mesh=mesh,
                                   in_specs=P(("dcn", "ici")),
                                   out_specs=P(("dcn", "ici")),
                                   check_vma=False)
            else:
                def body(row):  # [1, L]
                    x = row[0].astype(jnp.float32)
                    if prescale != 1.0:
                        x = x * np.float32(prescale)
                    out = q_hop(x, MESH_AXIS, size)
                    if average:
                        out = out / np.float32(size)
                    if postscale != 1.0:
                        out = out * np.float32(postscale)
                    return out.astype(dtype)[None]

                sm = jax.shard_map(body, mesh=self._mesh,
                                   in_specs=P(MESH_AXIS),
                                   out_specs=P(MESH_AXIS),
                                   check_vma=False)
            fn = jax.jit(sm)
            self._fn_cache[key] = fn
        return fn

    def _allreduce_bf16_fn(self, n: int, length: int, dtype: str,
                           average: bool, prescale: float, postscale: float):
        """bf16 cast wire as one compiled program: psum_scatter +
        all_gather with both hops in bfloat16 (half the exact wire's
        bytes, no block scales). This is the adaptive selector's fallback
        grid for heavy-tailed buckets that fail the int8/int4 residual
        test — the entry was enqueued under the identity compressor, so
        the cast must happen inside the executor's program, mirroring the
        ICI legs of the int8-dcn hierarchical form."""
        key = ("allreduce_bf16", n, length, dtype, average, prescale,
               postscale)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P

            size = self._world
            pad = (-length) % size

            def body(row):  # [1, L]: this rank's contribution
                x = row[0].astype(jnp.float32)
                if prescale != 1.0:
                    x = x * np.float32(prescale)
                x = x.astype(jnp.bfloat16)  # wire format: both hops bf16
                if pad:
                    x = jnp.pad(x, (0, pad))
                s = lax.psum_scatter(x, MESH_AXIS, scatter_dimension=0,
                                     tiled=True)
                out = lax.all_gather(s, MESH_AXIS,
                                     tiled=True).astype(jnp.float32)
                if pad:
                    out = out[:length]
                if average:
                    out = out / np.float32(size)
                if postscale != 1.0:
                    out = out * np.float32(postscale)
                return out.astype(dtype)[None]

            sm = jax.shard_map(body, mesh=self._mesh,
                               in_specs=P(MESH_AXIS),
                               out_specs=P(MESH_AXIS),
                               check_vma=False)
            fn = jax.jit(sm)
            self._fn_cache[key] = fn
        return fn

    def _adasum_fn(self, n: int, length: int, dtype: str):
        """Adasum scale-invariant reduction (reference `adasum/adasum.h:185-331`).

        The reference implements recursive vector-halving distance-doubling over
        MPI; on TPU the pairwise combine tree is expressed directly and XLA
        schedules the collectives. Combine rule (adasum.h:331+):
        ``a' = (1 - dot/(2|a|^2)) a + (1 - dot/(2|b|^2)) b``, zero-norm guarded.
        Requires power-of-2 world size (parity: `torch/mpi_ops.py:104-120`).
        """
        key = ("adasum", n, length, dtype)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(self._mesh, P(MESH_AXIS))

            def combine(a, b):
                # accumulate dots/norms in f32 for bf16 stability
                af = a.astype(jnp.float32)
                bf = b.astype(jnp.float32)
                dot = jnp.sum(af * bf, axis=1, keepdims=True)
                na = jnp.sum(af * af, axis=1, keepdims=True)
                nb = jnp.sum(bf * bf, axis=1, keepdims=True)
                ac = jnp.where(na == 0, 1.0, 1.0 - dot / (2.0 * jnp.where(na == 0, 1.0, na)))
                bc = jnp.where(nb == 0, 1.0, 1.0 - dot / (2.0 * jnp.where(nb == 0, 1.0, nb)))
                return (ac * af + bc * bf).astype(a.dtype)

            def kernel(g):
                buf = g
                m = buf.shape[0]
                while m > 1:
                    buf = combine(buf[0::2], buf[1::2])
                    m //= 2
                return jnp.broadcast_to(buf, (n, length))

            fn = jax.jit(kernel, out_shardings=sharding)
            self._fn_cache[key] = fn
        return fn

    def _allgather_assemble_fn(self, world: int, lmax: int, dtype: str,
                               ecounts: Tuple[Tuple[int, ...], ...],
                               tails: Tuple[Tuple[int, ...], ...],
                               d0s: Tuple[int, ...]):
        """ONE compiled program: gather the padded per-rank buffers and
        assemble every output tensor, leaving the results replicated on the
        rank devices. Replaces the round-2 per-destination host
        ``device_put`` loop (quadratic host traffic in world × tensor size)
        — on-device assembly keeps per-rank host traffic zero regardless of
        world size. ``ecounts[t][src]`` = element count tensor ``t``
        contributes from rank ``src``; ``tails[t]`` = trailing shape.
        Honors HOROVOD_HIERARCHICAL_ALLGATHER with the two-level
        ici-then-dcn gather (`mpi_operations.cc:168-310`'s node-leader
        decomposition)."""
        key = ("allgatherA", world, lmax, dtype, ecounts, tails, d0s,
               self._hier_allgather)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import NamedSharding, PartitionSpec as P

            nt = len(tails)
            offs = [[sum(ecounts[u][src] for u in range(t))
                     for src in range(world)] for t in range(nt)]

            def assemble(full):
                outs = []
                for t, tail in enumerate(tails):
                    segs = [full[src, offs[t][src]:offs[t][src]
                                 + ecounts[t][src]]
                            for src in range(world)]
                    cat = jnp.concatenate(segs) if len(segs) > 1 else segs[0]
                    outs.append(cat.reshape((d0s[t],) + tuple(tail)))
                return tuple(outs)

            if self._hier_allgather:
                mesh = self._mesh2

                def gather(row):  # [1, lmax] per device
                    g1 = lax.all_gather(row, "ici", axis=0, tiled=True)
                    return lax.all_gather(g1, "dcn", axis=0, tiled=True)

                sm = jax.shard_map(gather, mesh=mesh,
                                   in_specs=P(("dcn", "ici")), out_specs=P(),
                                   check_vma=False)
                fn = jax.jit(
                    lambda g: assemble(sm(g)),
                    out_shardings=NamedSharding(mesh, P()))
            else:
                # GSPMD inserts the all-gather: inputs row-sharded, outputs
                # replicated
                fn = jax.jit(assemble,
                             out_shardings=NamedSharding(self._mesh, P()))
            self._fn_cache[key] = fn
        return fn

    def _broadcast_fn(self, n: int, length: int, dtype: str, root: int):
        key = ("broadcast", n, length, dtype, root)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(self._mesh, P(MESH_AXIS))

            def kernel(g):
                row = jax.lax.dynamic_slice_in_dim(g, root, 1, axis=0)
                return jnp.broadcast_to(row, (n, length))

            fn = jax.jit(kernel, out_shardings=sharding)
            self._fn_cache[key] = fn
        return fn

    def _alltoall_fn(self, n: int, length: int, dtype: str):
        """Equal-split all-to-all: block transpose over the rank axis."""
        key = ("alltoall", n, length, dtype)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(self._mesh, P(MESH_AXIS))
            seg = length // n

            def kernel(g):
                b = g.reshape(n, n, seg)  # [src, dst, seg]
                t = b.transpose(1, 0, 2)  # [dst, src, seg] -> XLA all-to-all
                return t.reshape(n, length)

            fn = jax.jit(kernel, out_shardings=sharding)
            self._fn_cache[key] = fn
        return fn

    # ---------------------------------------------------------------- execute
    def execute(self, response: Response,
                entries_by_rank: Dict[int, List[TensorTableEntry]]):
        """Run one fused response; returns {rank: [result arrays in name order]}.

        The contract mirrors OperationManager::ExecuteOperation
        (`ops/operation_manager.cc:87-104`) + PerformOperation
        (`operations.cc:227-304`).
        """
        rt = response.response_type
        self.last_wire_mode = ""
        self.last_wire_bytes = 0
        if rt in (ResponseType.ALLREDUCE, ResponseType.ADASUM):
            return self._exec_allreduce(response, entries_by_rank,
                                        adasum=(rt == ResponseType.ADASUM))
        if rt == ResponseType.ALLGATHER:
            return self._exec_allgather(response, entries_by_rank)
        if rt == ResponseType.BROADCAST:
            return self._exec_broadcast(response, entries_by_rank)
        if rt == ResponseType.ALLTOALL:
            return self._exec_alltoall(response, entries_by_rank)
        raise ValueError(f"unsupported response type {rt}")

    def _exec_allreduce(self, response, entries_by_rank, adasum):
        import jax.numpy as jnp

        world = self._world
        if self._multiproc and response.tensor_shapes:
            return self._exec_allreduce_mp(response, entries_by_rank, adasum)
        ranks = sorted(entries_by_rank)
        template = entries_by_rank[ranks[0]]
        shapes = [tuple(e.array.shape) for e in template]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        dtype = _np_dtype(template[0].array)
        length = int(sum(sizes))
        e0 = template[0]

        if world == 1:
            out = [e.array for e in template]
            if not adasum and e0.prescale_factor * e0.postscale_factor != 1.0:
                f = e0.prescale_factor * e0.postscale_factor
                out = [a * np.asarray(f, a.dtype) for a in out]
            return {ranks[0]: out}

        bufs = []
        for r in self._local_ranks:
            if r in entries_by_rank:
                bufs.append(self._pack(entries_by_rank[r]))
            else:
                # joined rank contributes zeros (JoinOp semantics,
                # controller.cc:202-256, operations.cc:908-934)
                z = jnp.zeros((length,), dtype=dtype)
                bufs.append(self._jax.device_put(z, self._rank_devices[r]))
        wire = self._effective_wire(response, entries_by_rank, dtype,
                                    length, adasum)
        algo = self._algo_choice()
        hier = not adasum and not wire and (
            self._hier_allreduce or (algo == "hier"
                                     and self._mesh2 is not None))
        tree = (algo == "tree" and not adasum and not wire and not hier
                and world > 1 and (world & (world - 1)) == 0
                and np.issubdtype(np.dtype(dtype), np.floating)
                and np.dtype(dtype).itemsize <= 4)
        two_level = hier or (wire == "int8-dcn" and self._mesh2 is not None)
        g = self._global_array(bufs, length,
                               self._row_sharding2() if two_level else None)
        if adasum:
            fn = self._adasum_fn(world, length, dtype)
        elif wire == "bf16":
            fn = self._allreduce_bf16_fn(world, length, dtype,
                                         response.average,
                                         e0.prescale_factor,
                                         e0.postscale_factor)
        elif wire:
            fn = self._allreduce_q_fn(world, length, dtype, response.average,
                                      e0.prescale_factor,
                                      e0.postscale_factor, wire)
        elif tree:
            fn = self._allreduce_tree_fn(world, length, dtype,
                                         response.average,
                                         e0.prescale_factor,
                                         e0.postscale_factor)
        elif hier:
            fn = self._allreduce2_fn(world, length, dtype, response.average,
                                     e0.prescale_factor, e0.postscale_factor)
        else:
            fn = self._allreduce_fn(world, length, dtype, response.average,
                                    e0.prescale_factor, e0.postscale_factor)
        self._record_wire(wire, length, dtype,
                          "tree" if tree else ("hier" if hier else "ring"))
        out = fn(g)
        rows = self._shard_by_rank(out)
        return {
            r: self._unpack_row(rows[r], shapes, sizes)
            for r in ranks
        }

    def _record_wire(self, wire: str, length: int, dtype: str,
                     algorithm: str = "ring") -> None:
        self.last_wire_mode = wire
        self.last_algorithm = algorithm
        if wire == "bf16":
            # cast wire: scatter + gather, 2 bytes/element, no scales
            self.last_wire_bytes = 2 * length * 2
        elif wire:
            self.last_wire_bytes = self.quantized_wire_layout(
                length, self._world,
                bits=4 if wire == "int4" else 8)["wire_bytes"]
        else:
            self.last_wire_bytes = 2 * length * np.dtype(dtype).itemsize
        from .. import spmd as _spmd
        _spmd._note_algorithm(algorithm, length)

    def _exec_allreduce_mp(self, response, entries_by_rank, adasum):
        """Coordinated multiprocess allreduce/adasum: shapes, dtype and scale
        factors come from the negotiated Response so a joined rank (no local
        entries) still executes the identical multi-controller program,
        contributing zeros (`controller.cc:202-256`, `operations.cc:908-934`).
        """
        import jax.numpy as jnp

        world = self._world
        r = self._self_rank
        shapes = [tuple(s) for s in response.tensor_shapes]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        dtype = response.tensor_dtype
        length = int(sum(sizes))

        entries = entries_by_rank.get(r)
        if entries is not None:
            buf = self._pack(entries)
        else:
            buf = self._jax.device_put(jnp.zeros((length,), dtype=dtype),
                                       self._rank_devices[r])
        wire = self._effective_wire(response, entries_by_rank, dtype,
                                    length, adasum)
        algo = self._algo_choice()
        hier = not adasum and not wire and (
            self._hier_allreduce or (algo == "hier"
                                     and self._mesh2 is not None))
        tree = (algo == "tree" and not adasum and not wire and not hier
                and world > 1 and (world & (world - 1)) == 0
                and np.issubdtype(np.dtype(dtype), np.floating)
                and np.dtype(dtype).itemsize <= 4)
        two_level = hier or (wire == "int8-dcn" and self._mesh2 is not None)
        g = self._global_array([buf], length,
                               self._row_sharding2() if two_level else None)
        if adasum:
            fn = self._adasum_fn(world, length, dtype)
        elif wire == "bf16":
            fn = self._allreduce_bf16_fn(world, length, dtype,
                                         response.average,
                                         response.prescale,
                                         response.postscale)
        elif wire:
            fn = self._allreduce_q_fn(world, length, dtype, response.average,
                                      response.prescale, response.postscale,
                                      wire)
        elif tree:
            fn = self._allreduce_tree_fn(world, length, dtype,
                                         response.average,
                                         response.prescale,
                                         response.postscale)
        elif hier:
            fn = self._allreduce2_fn(world, length, dtype, response.average,
                                     response.prescale, response.postscale)
        else:
            fn = self._allreduce_fn(world, length, dtype, response.average,
                                    response.prescale, response.postscale)
        self._record_wire(wire, length, dtype,
                          "tree" if tree else ("hier" if hier else "ring"))
        out = fn(g)
        if entries is None:
            self._jax.block_until_ready(out)
            return {}
        rows = self._shard_by_rank(out)
        return {r: self._unpack_row(rows[r], shapes, sizes)}

    def _unpack_row(self, row, shapes, sizes):
        # row: (1, L) on the rank's device; slice back out
        # (MemcpyOutFusionBuffer analogue)
        flat = row.reshape(-1)
        outs, off = [], 0
        for shp, sz in zip(shapes, sizes):
            outs.append(flat[off:off + sz].reshape(shp))
            off += sz
        return outs

    def _exec_allgather(self, response, entries_by_rank):
        world = self._world
        if self._multiproc and response.tensor_sizes:
            return self._exec_allgather_mp(response, entries_by_rank)
        ranks = sorted(entries_by_rank)
        nt = len(entries_by_rank[ranks[0]])
        # per-rank buffer layout and lengths (ragged -> pad to max)
        sizes = {r: [int(np.prod(e.array.shape)) if e.array.shape else 1
                     for e in entries_by_rank[r]] for r in ranks}
        lengths = {r: sum(sizes[r]) for r in ranks}
        dtype = _np_dtype(entries_by_rank[ranks[0]][0].array)

        if world == 1:
            return {ranks[0]: [e.array for e in entries_by_rank[ranks[0]]]}

        lmax = max(lengths.values())
        bufs = [self._pack(entries_by_rank[r], pad_to=lmax)
                for r in self._local_ranks]
        sharding = self._row_sharding2() if self._hier_allgather else None
        g = self._global_array(bufs, lmax, sharding)
        ecounts = tuple(tuple(sizes[src][t] for src in range(world))
                        for t in range(nt))
        tails = tuple(tuple(entries_by_rank[ranks[0]][t].array.shape[1:])
                      for t in range(nt))
        d0s = tuple(sum(int(entries_by_rank[src][t].array.shape[0])
                        for src in range(world)) for t in range(nt))
        outs = self._allgather_assemble_fn(world, lmax, dtype, ecounts,
                                           tails, d0s)(g)
        # the outputs are replicated over the rank devices — every rank
        # reads its local copy; nothing moves through the host
        return {r: list(outs) for r in ranks}

    def _exec_allgather_mp(self, response, entries_by_rank):
        """Coordinated multiprocess allgather: every rank's dim0 comes from
        the negotiated ``Response.tensor_sizes`` (the reference's allgatherv
        displacement math, `collective_operations.h:91-125`), so ragged
        gathers work with only the local entries visible."""
        world = self._world
        r = self._self_rank
        entries = entries_by_rank[r]  # allgather+join is rejected upstream
        nt = len(response.tensor_names)
        tails = [tuple(s[1:]) for s in response.tensor_shapes]
        elems = [int(np.prod(t)) if t else 1 for t in tails]
        dtype = response.tensor_dtype
        # per-source total buffer length (entries packed in response order)
        len_r = [sum(int(response.tensor_sizes[t][src]) * elems[t]
                     for t in range(nt)) for src in range(world)]
        lmax = max(len_r)

        buf = self._pack(entries, pad_to=lmax)
        sharding = self._row_sharding2() if self._hier_allgather else None
        g = self._global_array([buf], lmax, sharding)
        ecounts = tuple(
            tuple(int(response.tensor_sizes[t][src]) * elems[t]
                  for src in range(world))
            for t in range(nt))
        d0s = tuple(int(sum(response.tensor_sizes[t])) for t in range(nt))
        outs = self._allgather_assemble_fn(world, lmax, dtype, ecounts,
                                           tuple(tails), d0s)(g)
        # the jit outputs are GLOBAL replicated arrays spanning other
        # processes' devices; hand the user this process's on-device copy
        # (single-device, fully addressable, no host round-trip) so results
        # chain into further ops — a global array would fail device_put
        return {r: [o.addressable_data(0) for o in outs]}

    def _exec_broadcast(self, response, entries_by_rank):
        world = self._world
        ranks = sorted(entries_by_rank)
        template = entries_by_rank[ranks[0]]
        shapes = [tuple(e.array.shape) for e in template]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        dtype = _np_dtype(template[0].array)
        length = int(sum(sizes))
        root = template[0].root_rank

        if world == 1:
            return {ranks[0]: [e.array for e in template]}

        bufs = [self._pack(entries_by_rank[r]) for r in self._local_ranks]
        g = self._global_array(bufs, length)
        out = self._broadcast_fn(world, length, dtype, root)(g)
        rows = self._shard_by_rank(out)
        return {r: self._unpack_row(rows[r], shapes, sizes) for r in ranks}

    def _exec_alltoall(self, response, entries_by_rank):
        world = self._world
        ranks = sorted(entries_by_rank)
        template = entries_by_rank[ranks[0]]
        if response.tensor_sizes or template[0].splits is not None:
            return self._exec_alltoallv(response, entries_by_rank)
        shapes = [tuple(e.array.shape) for e in template]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        dtype = _np_dtype(template[0].array)
        length = int(sum(sizes))

        if world == 1:
            return {ranks[0]: [e.array for e in template]}

        bufs = [self._pack(entries_by_rank[r]) for r in self._local_ranks]
        g = self._global_array(bufs, length)
        out = self._alltoall_fn(world, length, dtype)(g)
        rows = self._shard_by_rank(out)
        return {r: self._unpack_row(rows[r], shapes, sizes) for r in ranks}

    # ------------------------------------------------------ ragged alltoall
    def _a2av_pack_fn(self, splits, elem: int, maxc: int, dtype: str):
        """Per-source spread: flat input -> [world * maxc * elem] with each
        destination's chunk padded to ``maxc`` rows at its slot (the send
        side of the alltoallv displacement table)."""
        key = ("a2av_pack", splits, elem, maxc, dtype)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp

            offs = [sum(splits[:d]) for d in range(len(splits))]

            def kernel(flat):
                parts = []
                for d, s in enumerate(splits):
                    seg = flat[offs[d] * elem:(offs[d] + s) * elem]
                    if s < maxc:
                        seg = jnp.pad(seg, (0, (maxc - s) * elem))
                    parts.append(seg)
                return jnp.concatenate(parts)

            fn = jax.jit(kernel)
            self._fn_cache[key] = fn
        return fn

    def _a2av_unpack_fn(self, counts, tail, maxc: int, elem: int,
                        dtype: str):
        """Receive side: one rank's transposed row [world * maxc * elem] ->
        [sum(counts), *tail] by slicing each source's live rows."""
        key = ("a2av_unpack", counts, tail, maxc, elem, dtype)
        fn = self._fn_cache.get(key)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp

            d0 = int(sum(counts))

            def kernel(row):
                segs = [row[src * maxc * elem:
                            (src * maxc + counts[src]) * elem]
                        for src in range(len(counts))]
                cat = jnp.concatenate(segs) if len(segs) > 1 else segs[0]
                return cat.reshape((d0,) + tuple(tail))

            fn = jax.jit(kernel)
            self._fn_cache[key] = fn
        return fn

    def _exec_alltoallv(self, response, entries_by_rank):
        """Ragged alltoall (`alltoall(tensor, splits)`): the padded-chunk
        program — every (src, dst) chunk padded to the max row count, then
        the SAME splits-independent block transpose as the equal path, then
        per-destination slicing. Padding keeps the compiled collective
        reusable across splits patterns; pack/unpack recompile per pattern
        (they are cheap elementwise programs)."""
        world = self._world
        ranks = sorted(entries_by_rank)
        template = entries_by_rank[ranks[0]]
        tail = tuple(template[0].array.shape[1:])
        elem = int(np.prod(tail)) if tail else 1
        dtype = _np_dtype(template[0].array)

        if response.tensor_sizes:
            # negotiated matrix (coordinated plane): row-major by source
            flat = [int(v) for v in response.tensor_sizes[0]]
            matrix = [flat[r * world:(r + 1) * world] for r in range(world)]
        else:
            # local plane: every rank's entry (and its splits) is visible
            matrix = [list(entries_by_rank[r][0].splits) for r in ranks]

        if world == 1:
            return {ranks[0]: [AlltoallvResult(e.array,
                                               (int(e.array.shape[0]),))
                               for e in template]}

        maxc = max(1, max(max(row) for row in matrix))
        rowlen = world * maxc * elem

        bufs = []
        for r in self._local_ranks:
            e = entries_by_rank[r][0]
            flat_in = self._pack([e])
            bufs.append(self._a2av_pack_fn(tuple(matrix[r]), elem, maxc,
                                           dtype)(flat_in))
        g = self._global_array(bufs, rowlen)
        out = self._alltoall_fn(world, rowlen, dtype)(g)
        rows = self._shard_by_rank(out)
        res = {}
        for r in ranks:
            counts = tuple(matrix[src][r] for src in range(world))
            row = rows[r].reshape(-1)
            out_r = self._a2av_unpack_fn(counts, tail, maxc, elem,
                                         dtype)(row)
            # received splits ride the result (later-horovod's
            # ``(output, received_splits)`` API shape) — they are column r
            # of the negotiated send matrix, already in hand here
            res[r] = [AlltoallvResult(out_r, counts)]
        return res
