"""Warm-standby coordinator: replication client + promotion logic.

With ``HOROVOD_STANDBY_COORD`` set on an elastic job, rank 1 runs a
:class:`StandbyCoordinator` beside its ordinary worker role. It holds a
second connection to rank 0 announced with ``MSG_REPL_HELLO``; the primary
answers with one ``MSG_SNAPSHOT`` of the durable coordinator state and then
streams a ``MSG_JOURNAL`` record per membership-epoch change.

The replicated state is deliberately tiny. Rank 0's death always implies a
membership reset — rank 0 was a member — so a promoted standby never needs
the in-flight negotiation barriers, replay caches, or response tables: it
rebuilds a fresh ``CoordState``, restores the durable fields (epoch,
members, cache-id high-water mark), and immediately declares rank 0 lost.
Every survivor then walks the PR-4 machinery it already has: reconnect with
backoff (finding the promoted address under ``addr.{gen}.f1``), RESUME,
replay, ``RESP_RANKS_CHANGED``, elastic restore/sync. The cache-id
high-water mark is restored so ids the old primary handed out are never
reused for different tensors; the ids themselves die with the epoch bump
(survivors clear their sig caches on RANKS_CHANGED).

Promotion triggers on replication-stream loss WITHOUT a prior ``MSG_BYE``
(a clean shutdown sends BYE precisely so the standby stands down), after a
few quick re-dials to ride out transient blips. One failover deep by
design: the promoted coordinator does not accept a new standby.

This composes with the hierarchical control plane: the promoted server is
a full :class:`CoordinatorServer`, so it re-admits sub-coordinator
``MSG_BATCH``/``MSG_BATCH_HB`` (and N-tier ``MSG_TBATCH``/``MSG_THB``)
connections, and each sub-coordinator re-ships its in-flight batch ledger
on RESUME — replay caches make that idempotent. Mid-tier aggregator slots
have their own lighter failover (``hierarchy.TierStandby``): they hold no
durable state, so their standby probes TCP liveness and starts a stateless
replacement without touching this journal. Journal records tagged with a
subtree only replicate to sinks scoped to that subtree (plus this global
root stream), keeping rank-0 replication work bounded by its direct
children.

See docs/control-plane.md.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Callable, List, Optional

from ..metrics import instruments
from .. import blackbox as _blackbox
from .. import faultinject
from ..exceptions import ShutdownError
from . import lease as _lease_mod
from . import wire
from .coordinator import (MSG_BYE, MSG_FENCED, MSG_JOURNAL, MSG_REPL_HELLO,
                          MSG_SNAPSHOT, CoordinatorServer, _advertise_host,
                          _publish_key)

logger = logging.getLogger("horovod_tpu")


def dial_repl(addr, secret: str, rank: int, hello_payload: bytes = b"",
              timeout: float = 5.0, faults=None, peer: Optional[int] = None,
              fence: int = 0) -> socket.socket:
    """Open a replication-framed stream: connect and send MSG_REPL_HELLO.
    The hello payload names the stream's role — empty for a standby
    coordinator, a subtree tag for a sharded standby, ``push:{index}`` /
    ``fetch:{index}`` for checkpoint buddy journaling (ckpt/buddy.py).
    ``faults``/``peer`` wrap the socket for fault injection attributed to
    the given remote rank (partition rules); ``fence`` stamps the hello
    with the dialer's fencing epoch."""
    sock = socket.create_connection(addr, timeout=timeout)
    sock.settimeout(0.5)
    if faults is not None:
        sock = faults.wrap(sock)
        sock.set_peer(peer)
    wire.send_frame(sock, secret, MSG_REPL_HELLO, 0, rank, hello_payload,
                    fence=fence)
    return sock


class StandbyCoordinator:
    """Rank 1's warm standby: replicates the primary's durable state and
    promotes itself when the replication stream dies unannounced."""

    def __init__(self, rank: int, gen: int, host: str, port: int,
                 secret: str, make_state: Callable,
                 should_promote: Callable[[], bool]):
        self._rank = rank
        self._gen = gen
        self._addr = (host, port)
        self._secret = secret
        self._make_state = make_state
        self._should_promote = should_promote
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # replica of the primary's durable state, updated per frame
        self._have_snapshot = False
        self._jseq = 0
        self._epoch = 0
        self._world = 0
        self._elastic = True
        self._members: List[int] = []
        self._next_cache_id = 0
        self.promoted = False
        self.server: Optional[CoordinatorServer] = None
        # fenced leadership (runtime/lease.py): with the lease enabled the
        # standby NEVER promotes on stream loss alone — only by acquiring
        # the lease after observing a full TTL of stasis on its own clock
        self._faults = faultinject.for_rank(rank)
        self._guard = wire.FenceGuard(rank=rank)
        self._lease = (_lease_mod.LeaseManager(gen, rank)
                       if _lease_mod.lease_enabled() else None)
        self._lease_watching = False
        self._thread = threading.Thread(
            target=self._run, name="hvd_standby", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        """Intentional stand-down (worker shutdown/interrupt): never treat
        the teardown that follows as a dead primary."""
        self._stop.set()
        if self._lease is not None:
            self._lease.stop()
        with self._lock:
            server = self.server
        if server is not None:
            # release any exchange still blocked in the promoted state
            # machine with a proper shutdown response before freeing the
            # port — survivors see a clean coordinated shutdown, not a
            # second dead coordinator
            server.state.set_bye()
            server.stop()

    # ------------------------------------------------------------ replication
    def _dial(self) -> socket.socket:
        return dial_repl(self._addr, self._secret, self._rank,
                         faults=self._faults, peer=0,
                         fence=self._guard.epoch)

    def _run(self) -> None:
        sock: Optional[socket.socket] = None
        for _ in range(5):
            try:
                sock = self._dial()
                break
            except (ConnectionError, OSError):
                if self._stop.wait(0.2):
                    return
        if sock is None:
            logger.warning("standby: never reached the primary's "
                           "replication endpoint; standby inactive")
            return
        try:
            while not self._stop.is_set():
                try:
                    mt, _, _, payload = wire.recv_frame(sock, self._secret,
                                                        self._stop,
                                                        guard=self._guard)
                except ShutdownError:
                    return
                except wire.FenceError as exc:
                    # a frame stamped with a deposed epoch: once this
                    # standby holds the lease, that is the old primary
                    # confirming it fenced — the stream is done for good
                    logger.info("standby: deposed primary's frame rejected "
                                "(%s); replication stream closed", exc)
                    return
                except (ConnectionError, OSError) as exc:
                    if self._stop.is_set():
                        return
                    if self._lease is not None:
                        # lease mode: promotion belongs to the lease watcher
                        # alone; keep redialing through the outage so a
                        # revived (or healed) primary finds us again — and
                        # so a fenced one can tell us it fenced
                        redialed = self._redial_lease()
                        if redialed is None:
                            return
                        sock = redialed
                        continue
                    redialed = self._redial()
                    if redialed is not None:
                        sock = redialed
                        continue
                    if self._have_snapshot and self._should_promote():
                        self._promote(exc)
                    return
                if mt == MSG_SNAPSHOT:
                    (self._jseq, self._epoch, self._world, self._elastic,
                     self._members,
                     self._next_cache_id) = wire.decode_coord_snapshot(
                         payload)
                    self._have_snapshot = True
                    instruments.standby_journal_lag().labels(
                        tier="root").set(0)
                    if self._lease is not None and not self._lease_watching:
                        self._lease_watching = True
                        threading.Thread(target=self._lease_watch,
                                         name="hvd_lease_watch",
                                         daemon=True).start()
                elif mt == MSG_JOURNAL:
                    (self._jseq, self._epoch, self._members,
                     _reason) = wire.decode_coord_journal(payload)
                elif mt == MSG_FENCED:
                    # the primary self-fenced but we do not hold the lease
                    # (yet): the watcher decides promotion; keep redialing
                    logger.warning(
                        "standby: primary reports itself fenced (%s); "
                        "awaiting lease takeover",
                        payload.decode("utf-8", "replace") or "no reason")
                    redialed = self._redial_lease()
                    if redialed is None:
                        return
                    sock = redialed
                elif mt == MSG_BYE:
                    # clean coordinator end: stand down, never promote
                    logger.info("standby: primary said BYE; standing down")
                    return
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _redial(self) -> Optional[socket.socket]:
        """A few quick re-dials distinguish a transient blip from a dead
        primary; the real grace period is the workers' reconnect window."""
        for _ in range(3):
            if self._stop.wait(0.3):
                return None
            try:
                return self._dial()
            except (ConnectionError, OSError):
                continue
        return None

    def _redial_lease(self) -> Optional[socket.socket]:
        """Lease-mode redial: patient (a partition can outlast any sane
        blip window) but bounded — the lease watcher owns promotion, this
        loop only keeps a path open for the primary's BYE or FENCED."""
        for _ in range(120):
            if self._stop.wait(0.5):
                return None
            try:
                return self._dial()
            except (ConnectionError, OSError):
                continue
        return None

    # ---------------------------------------------------------- lease watcher
    def _lease_watch(self) -> None:
        """Observed-stasis takeover: poll the lease key and promote only
        after it sat UNCHANGED for a full TTL measured on this process's
        monotonic clock, and only by winning the CAS (runtime/lease.py).
        KV unreachability is never evidence of stasis — renewals may be
        happening where we cannot see them — so it resets the clock."""
        assert self._lease is not None
        poll = min(self._lease.renew_interval, 0.25)
        ttl = self._lease.ttl
        last_val: Optional[bytes] = None
        last_change = time.monotonic()
        while not self._stop.wait(poll):
            if self.promoted:
                return
            try:
                val = self._lease.read()
            except (ConnectionError, OSError):
                last_change = time.monotonic()
                continue
            if val != last_val:
                last_val = val
                last_change = time.monotonic()
                continue
            stasis = time.monotonic() - last_change
            if stasis < ttl:
                continue
            if not (self._have_snapshot and self._should_promote()):
                continue
            try:
                epoch = self._lease.acquire_over(val)
            except (ConnectionError, OSError):
                last_change = time.monotonic()
                continue
            if epoch is None:
                # lost the CAS race (another acquirer, or the holder came
                # back): restart observation from the new value
                last_val = None
                last_change = time.monotonic()
                continue
            self._guard.observe(epoch)
            self._promote(
                RuntimeError("leadership lease expired: %.1fs of observed "
                             "stasis (TTL %.1fs)" % (stasis, ttl)),
                fence_epoch=epoch)
            return

    # -------------------------------------------------------------- promotion
    def _promote(self, why: Exception, fence_epoch: int = 0) -> None:
        state = self._make_state()
        with state.cv:
            state.epoch = self._epoch
            state.members = set(self._members)
            state.committed = set()
            state.next_cache_id = self._next_cache_id
            state.jseq = self._jseq
        advertise = _advertise_host()
        bind = "127.0.0.1" if advertise == "127.0.0.1" else "0.0.0.0"
        server = CoordinatorServer(state, self._secret, host=bind,
                                   local_rank=self._rank)
        # stamp every frame the promoted coordinator sends with the epoch
        # it acquired the lease under — workers that saw it reject the old
        # primary's traffic from that instant on
        server.fence_epoch = fence_epoch
        # declare rank 0 lost BEFORE publishing the address: the first
        # worker to find us must already see the post-failover epoch, never
        # a window where the old membership looks intact
        state.rank_lost(0, "coordinator failover: rank 0 died (%s); "
                           "standby (rank 1) promoted" % (why,))
        with self._lock:
            self.server = server
            self.promoted = True
        if self._lease is not None:
            # the promoted coordinator is now the lease holder: renew it,
            # and fence OURSELVES if it is ever lost (symmetry — a
            # re-partitioned promotee obeys the same rule as the primary)
            self._lease.start_renewing(state.fence)
        _publish_key(f"addr.{self._gen}.f1",
                     f"{advertise}:{server.port}", self._secret)
        instruments.coord_failovers().inc()
        _blackbox.record(_blackbox.K_FAILOVER, "rank_%d" % self._rank,
                         "standby promoted to coordinator at %s:%d "
                         "(epoch %d -> %d, members %s)"
                         % (advertise, server.port, self._epoch,
                            state.epoch, sorted(state.members)),
                         rank=self._rank)
        logger.warning(
            "standby: replication stream died (%s); PROMOTED to "
            "coordinator at %s:%d, epoch %d, members %s",
            why, advertise, server.port, state.epoch,
            sorted(state.members))
