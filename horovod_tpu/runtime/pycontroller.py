"""Pure-Python fallback controller with the NativeController interface.

Used only when the C++ core cannot be built/loaded (``HVD_TPU_NATIVE=0`` or a
toolchain-less host). Semantics match `_core/controller.cc` exactly; the test
suite runs the same matrix against both (see tests/test_native.py).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..metrics import instruments
from ..utils.env import env_float as _env_float
from ..utils.timeline import Timeline
from .messages import RequestType, Response, ResponseType, TensorTableEntry


class _Meta:
    __slots__ = ("name", "rank", "type", "dtype", "shape", "root_rank",
                 "average", "prescale", "postscale", "handle", "enqueue_t",
                 "nbytes", "splits", "compression", "fusable")

    def __init__(self, e: TensorTableEntry, handle: int):
        self.name = e.tensor_name
        self.rank = e.rank
        self.type = e.request_type
        self.dtype = str(e.array.dtype)
        self.shape = tuple(e.array.shape)
        self.root_rank = e.root_rank
        self.average = e.average
        self.prescale = e.prescale_factor
        self.postscale = e.postscale_factor
        self.handle = handle
        self.enqueue_t = time.monotonic()
        self.nbytes = int(e.array.size) * e.array.dtype.itemsize
        self.splits = None if e.splits is None else tuple(int(s)
                                                          for s in e.splits)
        self.compression = e.compression
        self.fusable = e.fusable


class PyController:
    SUBMIT_DUPLICATE = -1
    SUBMIT_SHUTDOWN = -2
    SUBMIT_RANKS_CHANGED = -3

    def __init__(self, world: int, fusion_threshold: int,
                 stall_warning_s: float, stall_shutdown_s: float,
                 cache_capacity: int, fusion_enabled: bool,
                 timeline_path: Optional[str], autotune: bool,
                 cycle_time_ms: float, local_only: bool = False,
                 self_rank: int = 0):
        self._world = world
        self._local_only = local_only
        self._self_rank = self_rank
        self._threshold = fusion_threshold
        self._stall_warning_s = stall_warning_s
        self._stall_shutdown_s = stall_shutdown_s
        # enforced watchdog (read here, not a ctor arg: the ctor kwargs are
        # shared verbatim with NativeController, whose C++ signature is
        # fixed; 0 keeps the historical warn-only stall inspector)
        self._collective_timeout_s = _env_float(
            "HOROVOD_COLLECTIVE_TIMEOUT", 0.0)
        self._fusion_enabled = fusion_enabled
        self._cycle_ms = cycle_time_ms
        self._timeline = Timeline(timeline_path)
        self._next_handle = 0
        self._order: List[str] = []
        self._table: Dict[str, Dict[int, _Meta]] = {}
        self._joined: set = set()
        self._join_handles: Dict[int, int] = {}
        self._last_joined = -1
        self._shutdown = False
        self._warned: set = set()
        # elastic: ranks currently negotiating (None = fixed range(world));
        # membership epoch mirrors the coordinated controller's counter
        self._active_ranks: Optional[set] = None
        self._epoch = -1
        import threading
        self._lock = threading.Lock()

    def reset(self, ranks, epoch: int) -> List[int]:
        """Elastic membership reset: drop pending negotiation state, adopt
        the surviving rank set and epoch, and return the orphaned handles so
        the engine can fail them with RanksChangedError. Mirrors
        CoordState._reset_locked for the in-process controller."""
        with self._lock:
            orphans = [m.handle for st in self._table.values()
                       for m in st.values()]
            orphans.extend(self._join_handles.values())
            self._table.clear()
            self._order.clear()
            self._join_handles.clear()
            self._joined.clear()
            self._warned.clear()
            self._last_joined = -1
            self._active_ranks = set(ranks)
            self._epoch = epoch
        instruments.elastic_epoch().set(max(0, epoch))
        self._timeline.epoch_marker(epoch)
        return orphans

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def submit(self, entry: TensorTableEntry) -> int:
        with self._lock:
            if self._shutdown:
                return self.SUBMIT_SHUTDOWN
            ranks = self._table.setdefault(entry.tensor_name, {})
            if entry.rank in ranks:
                return self.SUBMIT_DUPLICATE
            h = self._next_handle
            self._next_handle += 1
            if not ranks:
                self._order.append(entry.tensor_name)
            ranks[entry.rank] = _Meta(entry, h)
            self._timeline.negotiate_start(entry.tensor_name, entry.rank)
            return h

    def join(self, rank: int) -> int:
        with self._lock:
            if self._shutdown:
                return self.SUBMIT_SHUTDOWN
            if rank in self._join_handles:  # repeated join: same barrier
                return self._join_handles[rank]
            h = self._next_handle
            self._next_handle += 1
            self._joined.add(rank)
            self._join_handles[rank] = h
            self._last_joined = rank
            return h

    # ------------------------------------------------------------- validate
    def _validate(self, name: str, ranks: Dict[int, _Meta]) -> Optional[str]:
        metas = list(ranks.values())
        e0 = metas[0]
        if any(m.type != e0.type for m in metas):
            return f"Mismatched collective operations for tensor '{name}'"
        if any(m.dtype != e0.dtype for m in metas):
            return f"Mismatched data types for tensor '{name}'"
        if any((m.average, m.prescale, m.postscale)
               != (e0.average, e0.prescale, e0.postscale) for m in metas):
            return f"Mismatched reduction op/scale factors for tensor '{name}'"
        if any(m.compression != e0.compression for m in metas):
            return (f"Mismatched compression for tensor '{name}': set "
                    "HOROVOD_COMPRESSION identically on every rank")
        a2a_ragged = (e0.type == RequestType.ALLTOALL
                      and e0.splits is not None)
        if e0.type in (RequestType.ALLREDUCE, RequestType.ADASUM,
                       RequestType.BROADCAST) or (
                e0.type == RequestType.ALLTOALL and not a2a_ragged):
            if any(m.shape != e0.shape for m in metas):
                return f"Mismatched tensor shapes for '{name}'"
        if e0.type == RequestType.ALLGATHER:
            if self._local_only and self._world > 1:
                return ("Allgather is not yet supported in multiprocess mode "
                        "(cross-process size negotiation not implemented).")
            if any(len(m.shape) == 0 for m in metas):
                return f"Allgather of scalar tensor '{name}' is not supported."
            if any(m.shape[1:] != e0.shape[1:] for m in metas):
                return ("Mismatched allgather tensor shapes beyond first "
                        f"dimension for '{name}'")
        if e0.type == RequestType.ADASUM and (self._world & (self._world - 1)):
            return (f"Adasum requires a power-of-2 number of ranks; got "
                    f"{self._world}.")
        if e0.type == RequestType.ALLTOALL:
            if any((m.splits is None) != (e0.splits is None) for m in metas):
                return (f"Mismatched alltoall splits usage for tensor "
                        f"'{name}': some ranks passed splits, others did "
                        "not.")
            if a2a_ragged:
                if self._local_only and self._world > 1:
                    return ("Ragged alltoall is not supported in "
                            "multiprocess mode without the cross-process "
                            "control plane (launch via hvdrun so ranks "
                            "share a coordinator address channel).")
                for m in metas:
                    if not m.shape:
                        return (f"Alltoall of scalar tensor '{name}' is "
                                "not supported.")
                    if len(m.splits) != self._world:
                        return (f"Alltoall splits for tensor '{name}' on "
                                f"rank {m.rank} has {len(m.splits)} "
                                f"entries; expected world size "
                                f"{self._world}.")
                    if any(s < 0 for s in m.splits):
                        return (f"Alltoall splits for tensor '{name}' on "
                                f"rank {m.rank} contains a negative entry.")
                    if sum(m.splits) != m.shape[0]:
                        return (f"Alltoall splits for tensor '{name}' on "
                                f"rank {m.rank} sum to {sum(m.splits)} but "
                                f"dim 0 is {m.shape[0]}.")
                    if m.shape[1:] != e0.shape[1:]:
                        return ("Mismatched alltoall tensor shapes beyond "
                                f"first dimension for '{name}'")
            else:
                d0 = e0.shape[0] if e0.shape else 0
                if not e0.shape or d0 % self._world != 0:
                    return (f"Alltoall tensor '{name}' first dimension "
                            f"({d0}) must be divisible by world size "
                            f"{self._world}.")
        if e0.type == RequestType.BROADCAST:
            if any(m.root_rank != e0.root_rank for m in metas):
                return f"Mismatched root ranks for broadcast '{name}'"
            if not (0 <= e0.root_rank < self._world):
                return (f"Invalid root rank {e0.root_rank} for broadcast "
                        f"'{name}' (world size {self._world}).")
        if self._joined and e0.type in (RequestType.ALLGATHER,
                                        RequestType.BROADCAST,
                                        RequestType.ALLTOALL):
            return (f"{e0.type.name} is not supported while a rank has "
                    "joined.")
        return None

    @staticmethod
    def _sig(m: _Meta):
        # compression included: quantized and plain buckets compile
        # different wire programs (see CoordState._fuse_sig)
        return (int(m.type), m.dtype, m.average, m.prescale, m.postscale,
                m.root_rank, m.compression)

    def tick(self):
        with self._lock:
            if self._shutdown:
                return None
            now = time.monotonic()
            if self._local_only:
                active = {self._self_rank} - self._joined
            elif self._active_ranks is not None:
                active = self._active_ranks - self._joined
            else:
                active = set(range(self._world)) - self._joined

            join_released: List[int] = []
            last_joined = -1
            if self._local_only:
                all_joined = self._self_rank in self._joined
            elif self._active_ranks is not None:
                all_joined = self._active_ranks <= self._joined
            else:
                all_joined = len(self._joined) == self._world
            if self._joined and all_joined and not self._table:
                join_released = list(self._join_handles.values())
                last_joined = self._last_joined
                self._join_handles.clear()
                self._joined.clear()
                return ([], [], join_released, last_joined, [], False)

            ready, waiting = [], []
            stall_warnings: List[str] = []
            stall_shutdown = False
            timed_out: List[Tuple[str, Dict[int, _Meta], List[int], float]] = []
            n_stalled = 0
            max_skew = -1.0
            for name in self._order:
                st = self._table.get(name)
                if st is None:
                    continue
                if active <= set(st.keys()):
                    ready.append(name)
                    if len(st) > 1:
                        # enqueue-time spread at readiness = how long the
                        # fast ranks waited on the straggler for this tensor
                        ts = [m.enqueue_t for m in st.values()]
                        max_skew = max(max_skew, max(ts) - min(ts))
                    # completed: re-arm the stall inspector so a second
                    # stall of the same tensor warns again
                    self._warned.discard(name)
                else:
                    waited = now - min(m.enqueue_t for m in st.values())
                    missing = sorted(active - set(st.keys()))
                    if (self._collective_timeout_s
                            and waited > self._collective_timeout_s):
                        # enforced watchdog: fail the submitted handles with
                        # a named error instead of warning forever
                        timed_out.append((name, self._table.pop(name),
                                          missing, waited))
                        self._warned.discard(name)
                        continue
                    waiting.append(name)
                    if waited > self._stall_warning_s:
                        n_stalled += 1
                        if name not in self._warned:
                            self._warned.add(name)
                            # same shape as the coordinated stall report:
                            # name the ranks this tensor is still waiting on
                            stall_warnings.append(
                                f"{name} (waiting on ranks {missing} for "
                                f"{int(waited)}s)")
                    if self._stall_shutdown_s and waited > self._stall_shutdown_s:
                        stall_shutdown = True
            instruments.stalled_tensors().set(n_stalled)
            if max_skew >= 0:
                instruments.straggler_skew_seconds().set(max_skew)
            self._order = waiting
            if (not ready and not stall_warnings and not stall_shutdown
                    and not timed_out):
                return None

            singles = []
            responses: List[Response] = []
            handle_pairs: List[List[Tuple[int, int]]] = []
            for name, st, missing, waited in timed_out:
                # hvd_collective_timeouts_total is counted in the engine's
                # ERROR-perform path, uniformly across controller kinds
                responses.append(Response(
                    ResponseType.ERROR, [name],
                    error_message=(
                        f"collective timeout: tensor '{name}' waited "
                        f"{int(waited)}s on ranks {missing} "
                        f"(HOROVOD_COLLECTIVE_TIMEOUT="
                        f"{self._collective_timeout_s:g}s exceeded)")))
                handle_pairs.append(sorted((r, m.handle)
                                           for r, m in st.items()))
            for name in ready:
                st = self._table.pop(name)
                pairs = sorted((r, m.handle) for r, m in st.items())
                err = self._validate(name, st)
                if err is not None:
                    responses.append(Response(ResponseType.ERROR, [name],
                                              error_message=err))
                    handle_pairs.append(pairs)
                    continue
                e0 = st[min(st)]
                singles.append((name, e0, pairs))

            used = [False] * len(singles)
            for i, (name, e0, pairs) in enumerate(singles):
                if used[i]:
                    continue
                used[i] = True
                bucket = [i]
                total = e0.nbytes
                # client-built buckets (fusable=False, backward-pass bucket
                # overlap) never merge: each stays its own response so its
                # wire can start while later buckets are still enqueueing
                fusable = self._fusion_enabled and e0.fusable and e0.type in (
                    RequestType.ALLREDUCE, RequestType.ADASUM,
                    RequestType.ALLGATHER)
                if fusable:
                    for j in range(i + 1, len(singles)):
                        if used[j]:
                            continue
                        if (singles[j][1].fusable
                                and self._sig(singles[j][1]) == self._sig(e0)
                                and total + singles[j][1].nbytes
                                <= self._threshold):
                            used[j] = True
                            bucket.append(j)
                            total += singles[j][1].nbytes
                resp = Response(ResponseType(int(e0.type)),
                                [singles[k][0] for k in bucket],
                                average=e0.average)
                resp.prescale = e0.prescale
                resp.postscale = e0.postscale
                resp.root_rank = e0.root_rank
                resp.compression = e0.compression
                hp: List[Tuple[int, int]] = []
                for k in bucket:
                    hp.extend(singles[k][2])
                responses.append(resp)
                handle_pairs.append(hp)
            return (responses, handle_pairs, join_released, last_joined,
                    stall_warnings, stall_shutdown)

    def shutdown(self) -> List[int]:
        with self._lock:
            if self._shutdown:
                return []
            self._shutdown = True
            orphans = [m.handle for st in self._table.values()
                       for m in st.values()]
            orphans.extend(self._join_handles.values())
            self._table.clear()
            self._order.clear()
            self._join_handles.clear()
            self._joined.clear()
        self._timeline.close()
        return orphans

    # ---- timeline / autotune
    def timeline_op_start(self, tensor: str, op: str) -> None:
        self._timeline.op_start(tensor, op)

    def timeline_activity(self, tensor: str, activity: str) -> None:
        self._timeline.activity(tensor, activity)

    def timeline_op_end(self, tensor: str) -> None:
        self._timeline.op_end(tensor)

    def timeline_cycle(self) -> None:
        self._timeline.cycle_tick()

    def timeline_cache(self, hits: int, misses: int) -> None:
        self._timeline.cache_counter(hits, misses)

    def report_score(self, nbytes: int, seconds: float) -> bool:
        return False  # autotune is a native-core feature

    def fusion_threshold(self) -> int:
        return self._threshold

    def cycle_time_ms(self) -> float:
        return self._cycle_ms

    def cache_stats(self):
        return (0, 0)
