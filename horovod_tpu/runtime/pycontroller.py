"""Pure-Python fallback controller with the NativeController interface.

Used only when the C++ core cannot be built/loaded (``HVD_TPU_NATIVE=0`` or a
toolchain-less host). Semantics match `_core/controller.cc` exactly; the test
suite runs the same matrix against both (see tests/test_native.py).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

from .. import blackbox as _blackbox
from ..metrics import instruments
from ..utils.env import env_float as _env_float
from ..utils.timeline import Timeline
from .messages import RequestType, Response, ResponseType, TensorTableEntry

logger = logging.getLogger("horovod_tpu")


class _Meta:
    __slots__ = ("name", "rank", "type", "dtype", "shape", "root_rank",
                 "average", "prescale", "postscale", "handle", "enqueue_t",
                 "nbytes", "splits", "compression", "fusable")

    def __init__(self, e: TensorTableEntry, handle: int):
        self.name = e.tensor_name
        self.rank = e.rank
        self.type = e.request_type
        self.dtype = str(e.array.dtype)
        self.shape = tuple(e.array.shape)
        self.root_rank = e.root_rank
        self.average = e.average
        self.prescale = e.prescale_factor
        self.postscale = e.postscale_factor
        self.handle = handle
        self.enqueue_t = time.monotonic()
        self.nbytes = int(e.array.size) * e.array.dtype.itemsize
        self.splits = None if e.splits is None else tuple(int(s)
                                                          for s in e.splits)
        self.compression = e.compression
        self.fusable = e.fusable


class PyController:
    SUBMIT_DUPLICATE = -1
    SUBMIT_SHUTDOWN = -2
    SUBMIT_RANKS_CHANGED = -3

    def __init__(self, world: int, fusion_threshold: int,
                 stall_warning_s: float, stall_shutdown_s: float,
                 cache_capacity: int, fusion_enabled: bool,
                 timeline_path: Optional[str], autotune: bool,
                 cycle_time_ms: float, local_only: bool = False,
                 self_rank: int = 0):
        self._world = world
        self._local_only = local_only
        self._self_rank = self_rank
        self._threshold = fusion_threshold
        self._stall_warning_s = stall_warning_s
        self._stall_shutdown_s = stall_shutdown_s
        # enforced watchdog (read here, not a ctor arg: the ctor kwargs are
        # shared verbatim with NativeController, whose C++ signature is
        # fixed; 0 keeps the historical warn-only stall inspector)
        self._collective_timeout_s = _env_float(
            "HOROVOD_COLLECTIVE_TIMEOUT", 0.0)
        self._fusion_enabled = fusion_enabled
        self._cycle_ms = cycle_time_ms
        self._timeline = Timeline(timeline_path)
        self._next_handle = 0
        self._order: List[str] = []
        self._table: Dict[str, Dict[int, _Meta]] = {}
        self._joined: set = set()
        self._join_handles: Dict[int, int] = {}
        self._last_joined = -1
        self._shutdown = False
        self._warned: set = set()
        # elastic: ranks currently negotiating (None = fixed range(world));
        # membership epoch mirrors the coordinated controller's counter
        self._active_ranks: Optional[set] = None
        self._epoch = -1
        # straggler policy (runtime/straggler.py): only meaningful when this
        # controller negotiates for SEVERAL ranks in one process; a
        # local-only controller sees exactly one rank's arrivals, so there
        # is no spread to act on and the policy stays off (= the
        # NativeController, which never emits an exclusion — the
        # "absent ⇒ full participation" agreement across controllers)
        self._straggler = None
        if not local_only and world > 1:
            from . import straggler as straggler_mod
            self._straggler = straggler_mod.StragglerPolicy.from_env()
        self._round = 0
        # name -> {rank: owed} solo-completion credits: one credit per
        # partial round negotiated without that (excluded) rank. The rank
        # trails by as many steps as there were partial rounds, so each
        # trailing enqueue consumes ONE credit and completes as a solo
        # self-reduction instead of stalling forever — a set would
        # undercount a rank that is several steps behind
        self._skipped: Dict[str, Dict[int, int]] = {}
        import threading
        self._lock = threading.Lock()

    def reset(self, ranks, epoch: int) -> List[int]:
        """Elastic membership reset: drop pending negotiation state, adopt
        the surviving rank set and epoch, and return the orphaned handles so
        the engine can fail them with RanksChangedError. Mirrors
        CoordState._reset_locked for the in-process controller."""
        with self._lock:
            orphans = [m.handle for st in self._table.values()
                       for m in st.values()]
            orphans.extend(self._join_handles.values())
            self._table.clear()
            self._order.clear()
            self._join_handles.clear()
            self._joined.clear()
            self._warned.clear()
            self._last_joined = -1
            self._active_ranks = set(ranks)
            self._epoch = epoch
            self._skipped.clear()
            if self._straggler is not None:
                self._straggler.reset()
        instruments.elastic_epoch().set(max(0, epoch))
        self._timeline.epoch_marker(epoch)
        return orphans

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def excluded_ranks(self) -> frozenset:
        """Ranks currently excluded by the straggler policy (empty when the
        policy is off — same accessor across all controllers)."""
        with self._lock:
            if self._straggler is None:
                return frozenset()
            return frozenset(self._straggler.excluded)

    def submit(self, entry: TensorTableEntry) -> int:
        with self._lock:
            if self._shutdown:
                return self.SUBMIT_SHUTDOWN
            ranks = self._table.setdefault(entry.tensor_name, {})
            if entry.rank in ranks:
                return self.SUBMIT_DUPLICATE
            h = self._next_handle
            self._next_handle += 1
            if not ranks:
                self._order.append(entry.tensor_name)
            ranks[entry.rank] = _Meta(entry, h)
            self._timeline.negotiate_start(entry.tensor_name, entry.rank)
            return h

    def join(self, rank: int) -> int:
        with self._lock:
            if self._shutdown:
                return self.SUBMIT_SHUTDOWN
            if rank in self._join_handles:  # repeated join: same barrier
                return self._join_handles[rank]
            h = self._next_handle
            self._next_handle += 1
            self._joined.add(rank)
            self._join_handles[rank] = h
            self._last_joined = rank
            return h

    # ------------------------------------------------------------- validate
    def _validate(self, name: str, ranks: Dict[int, _Meta]) -> Optional[str]:
        metas = list(ranks.values())
        e0 = metas[0]
        if any(m.type != e0.type for m in metas):
            return f"Mismatched collective operations for tensor '{name}'"
        if any(m.dtype != e0.dtype for m in metas):
            return f"Mismatched data types for tensor '{name}'"
        if any((m.average, m.prescale, m.postscale)
               != (e0.average, e0.prescale, e0.postscale) for m in metas):
            return f"Mismatched reduction op/scale factors for tensor '{name}'"
        if any(m.compression != e0.compression for m in metas):
            return (f"Mismatched compression for tensor '{name}': set "
                    "HOROVOD_COMPRESSION identically on every rank")
        a2a_ragged = (e0.type == RequestType.ALLTOALL
                      and e0.splits is not None)
        if e0.type in (RequestType.ALLREDUCE, RequestType.ADASUM,
                       RequestType.BROADCAST) or (
                e0.type == RequestType.ALLTOALL and not a2a_ragged):
            if any(m.shape != e0.shape for m in metas):
                return f"Mismatched tensor shapes for '{name}'"
        if e0.type == RequestType.ALLGATHER:
            if self._local_only and self._world > 1:
                return ("Allgather is not yet supported in multiprocess mode "
                        "(cross-process size negotiation not implemented).")
            if any(len(m.shape) == 0 for m in metas):
                return f"Allgather of scalar tensor '{name}' is not supported."
            if any(m.shape[1:] != e0.shape[1:] for m in metas):
                return ("Mismatched allgather tensor shapes beyond first "
                        f"dimension for '{name}'")
        if e0.type == RequestType.ADASUM and (self._world & (self._world - 1)):
            return (f"Adasum requires a power-of-2 number of ranks; got "
                    f"{self._world}.")
        if e0.type == RequestType.ALLTOALL:
            if any((m.splits is None) != (e0.splits is None) for m in metas):
                return (f"Mismatched alltoall splits usage for tensor "
                        f"'{name}': some ranks passed splits, others did "
                        "not.")
            if a2a_ragged:
                if self._local_only and self._world > 1:
                    return ("Ragged alltoall is not supported in "
                            "multiprocess mode without the cross-process "
                            "control plane (launch via hvdrun so ranks "
                            "share a coordinator address channel).")
                for m in metas:
                    if not m.shape:
                        return (f"Alltoall of scalar tensor '{name}' is "
                                "not supported.")
                    if len(m.splits) != self._world:
                        return (f"Alltoall splits for tensor '{name}' on "
                                f"rank {m.rank} has {len(m.splits)} "
                                f"entries; expected world size "
                                f"{self._world}.")
                    if any(s < 0 for s in m.splits):
                        return (f"Alltoall splits for tensor '{name}' on "
                                f"rank {m.rank} contains a negative entry.")
                    if sum(m.splits) != m.shape[0]:
                        return (f"Alltoall splits for tensor '{name}' on "
                                f"rank {m.rank} sum to {sum(m.splits)} but "
                                f"dim 0 is {m.shape[0]}.")
                    if m.shape[1:] != e0.shape[1:]:
                        return ("Mismatched alltoall tensor shapes beyond "
                                f"first dimension for '{name}'")
            else:
                d0 = e0.shape[0] if e0.shape else 0
                if not e0.shape or d0 % self._world != 0:
                    return (f"Alltoall tensor '{name}' first dimension "
                            f"({d0}) must be divisible by world size "
                            f"{self._world}.")
        if e0.type == RequestType.BROADCAST:
            if any(m.root_rank != e0.root_rank for m in metas):
                return f"Mismatched root ranks for broadcast '{name}'"
            if not (0 <= e0.root_rank < self._world):
                return (f"Invalid root rank {e0.root_rank} for broadcast "
                        f"'{name}' (world size {self._world}).")
        if self._joined and e0.type in (RequestType.ALLGATHER,
                                        RequestType.BROADCAST,
                                        RequestType.ALLTOALL):
            return (f"{e0.type.name} is not supported while a rank has "
                    "joined.")
        return None

    def _observe_full_row(self, row: Dict[int, float]) -> None:
        """Feed one full-house arrival row to the straggler policy and act
        on its transitions (runs under self._lock). The same events the
        coordinated controller records, so hvddoctor's chronic_straggler
        signature works identically against both planes."""
        from ..goodput import ledger as _goodput

        led = _goodput.active()
        pol = self._straggler
        events = pol.observe_round(row)
        for r in events["excluded"]:
            logger.warning(
                "straggler policy: excluding rank %d after %d late rounds; "
                "collectives proceed over the surviving subgroup",
                r, pol.patience)
            _blackbox.record(_blackbox.K_EXCLUDED, "rank_%d" % r,
                             "excluded episode=%d" % pol.episodes.get(r, 0))
            if led is not None:
                led.note_excluded(r, True)
        for r in events["readmitted"]:
            logger.info("straggler policy: re-admitting rank %d", r)
            _blackbox.record(_blackbox.K_EXCLUDED, "rank_%d" % r,
                             "readmitted")
            if led is not None:
                led.note_excluded(r, False)
        if events["excluded"] or events["readmitted"]:
            instruments.excluded_rank().set(
                max(pol.excluded) if pol.excluded else -1)

    @staticmethod
    def _sig(m: _Meta):
        # compression included: quantized and plain buckets compile
        # different wire programs (see CoordState._fuse_sig)
        return (int(m.type), m.dtype, m.average, m.prescale, m.postscale,
                m.root_rank, m.compression)

    def tick(self):
        with self._lock:
            if self._shutdown:
                return None
            now = time.monotonic()
            if self._local_only:
                active = {self._self_rank} - self._joined
            elif self._active_ranks is not None:
                active = self._active_ranks - self._joined
            else:
                active = set(range(self._world)) - self._joined
            # straggler policy: negotiate over the surviving subgroup; the
            # excluded rank's slot zero-fills in the executor (Join-op
            # semantics) and the engine rescales the average by
            # world / n_active (see Engine._perform_resp)
            full_house = set(active)
            excl: set = set()
            if self._straggler is not None and self._straggler.excluded:
                excl = set(self._straggler.excluded) & active
                active = active - excl or active

            join_released: List[int] = []
            last_joined = -1
            if self._local_only:
                all_joined = self._self_rank in self._joined
            elif self._active_ranks is not None:
                all_joined = self._active_ranks <= self._joined
            else:
                all_joined = len(self._joined) == self._world
            if self._joined and all_joined and not self._table:
                join_released = list(self._join_handles.values())
                last_joined = self._last_joined
                self._join_handles.clear()
                self._joined.clear()
                return ([], [], join_released, last_joined, [], False)

            ready, waiting = [], []
            stall_warnings: List[str] = []
            stall_shutdown = False
            timed_out: List[Tuple[str, Dict[int, _Meta], List[int], float]] = []
            n_stalled = 0
            max_skew = -1.0
            for name in self._order:
                st = self._table.get(name)
                if st is None:
                    continue
                have = set(st.keys())
                if (self._straggler is not None
                        and len(full_house) > 1 and full_house <= have):
                    # a full arrival row (excluded ranks included — their
                    # lateness IS the measurement) feeds the policy once
                    self._round += 1
                    self._observe_full_row(
                        {r: st[r].enqueue_t for r in full_house})
                    excl = set(self._straggler.excluded) & full_house
                    active = full_house - excl or full_house
                ready_now = active <= have
                if (ready_now and excl and not full_house <= have
                        and st[min(st)].type not in (RequestType.ALLREDUCE,
                                                     RequestType.ADASUM)):
                    # partial participation is a summable-gradient concept:
                    # a gather/broadcast/alltoall slot cannot be zero-filled
                    # without silently corrupting the result, so those ops
                    # keep waiting for the full house
                    ready_now = False
                if ready_now:
                    ready.append(name)
                    if len(st) > 1:
                        # enqueue-time spread at readiness = how long the
                        # fast ranks waited on the straggler for this tensor
                        ts = [m.enqueue_t for m in st.values()]
                        max_skew = max(max_skew, max(ts) - min(ts))
                    # completed: re-arm the stall inspector so a second
                    # stall of the same tensor warns again
                    self._warned.discard(name)
                elif (excl and have and have <= excl
                      and all(self._skipped.get(name, {}).get(r, 0) > 0
                              for r in have)):
                    # trailing enqueue(s) from ranks skipped when this name
                    # was negotiated without them: complete solo (the rank
                    # self-reduces; docs/fault-tolerance.md caveats) instead
                    # of stalling forever. Gated on CURRENT exclusion so a
                    # re-admitted rank's early enqueue merges into the next
                    # group round rather than self-reducing
                    owed = self._skipped[name]
                    for r in have:
                        owed[r] -= 1
                        if owed[r] <= 0:
                            del owed[r]
                    if not owed:
                        del self._skipped[name]
                    ready.append(name)
                else:
                    waited = now - min(m.enqueue_t for m in st.values())
                    missing = sorted(active - set(st.keys()))
                    if (self._collective_timeout_s
                            and waited > self._collective_timeout_s):
                        # enforced watchdog: fail the submitted handles with
                        # a named error instead of warning forever
                        timed_out.append((name, self._table.pop(name),
                                          missing, waited))
                        self._warned.discard(name)
                        continue
                    waiting.append(name)
                    if waited > self._stall_warning_s:
                        n_stalled += 1
                        if name not in self._warned:
                            self._warned.add(name)
                            # same shape as the coordinated stall report:
                            # name the ranks this tensor is still waiting on
                            stall_warnings.append(
                                f"{name} (waiting on ranks {missing} for "
                                f"{int(waited)}s)")
                    if self._stall_shutdown_s and waited > self._stall_shutdown_s:
                        stall_shutdown = True
            instruments.stalled_tensors().set(n_stalled)
            if max_skew >= 0:
                instruments.straggler_skew_seconds().set(max_skew)
            self._order = waiting
            if (not ready and not stall_warnings and not stall_shutdown
                    and not timed_out):
                return None

            singles = []
            responses: List[Response] = []
            handle_pairs: List[List[Tuple[int, int]]] = []
            for name, st, missing, waited in timed_out:
                # hvd_collective_timeouts_total is counted in the engine's
                # ERROR-perform path, uniformly across controller kinds
                responses.append(Response(
                    ResponseType.ERROR, [name],
                    error_message=(
                        f"collective timeout: tensor '{name}' waited "
                        f"{int(waited)}s on ranks {missing} "
                        f"(HOROVOD_COLLECTIVE_TIMEOUT="
                        f"{self._collective_timeout_s:g}s exceeded)")))
                handle_pairs.append(sorted((r, m.handle)
                                           for r, m in st.items()))
            for name in ready:
                st = self._table.pop(name)
                pairs = sorted((r, m.handle) for r, m in st.items())
                err = self._validate(name, st)
                if err is not None:
                    responses.append(Response(ResponseType.ERROR, [name],
                                              error_message=err))
                    handle_pairs.append(pairs)
                    continue
                e0 = st[min(st)]
                # ranks absent from this collective (straggler exclusion or
                # a trailing solo completion): the executor zero-fills their
                # slots, the engine rescales the average (messages.py)
                miss = frozenset((active | excl) - set(st))
                skipped = miss & excl
                if skipped:
                    owed = self._skipped.setdefault(name, {})
                    for r in skipped:
                        owed[r] = owed.get(r, 0) + 1
                singles.append((name, e0, pairs, miss))

            used = [False] * len(singles)
            for i, (name, e0, pairs, miss) in enumerate(singles):
                if used[i]:
                    continue
                used[i] = True
                bucket = [i]
                total = e0.nbytes
                # client-built buckets (fusable=False, backward-pass bucket
                # overlap) never merge: each stays its own response so its
                # wire can start while later buckets are still enqueueing
                fusable = self._fusion_enabled and e0.fusable and e0.type in (
                    RequestType.ALLREDUCE, RequestType.ADASUM,
                    RequestType.ALLGATHER)
                if fusable:
                    for j in range(i + 1, len(singles)):
                        if used[j]:
                            continue
                        if (singles[j][1].fusable
                                and self._sig(singles[j][1]) == self._sig(e0)
                                # never fuse across contributor sets: a rank
                                # with entries for only HALF a bucket would
                                # pack a short (wrong-offset) buffer
                                and singles[j][3] == miss
                                and total + singles[j][1].nbytes
                                <= self._threshold):
                            used[j] = True
                            bucket.append(j)
                            total += singles[j][1].nbytes
                resp = Response(ResponseType(int(e0.type)),
                                [singles[k][0] for k in bucket],
                                average=e0.average)
                if miss:
                    resp.excluded_ranks = sorted(miss)
                    instruments.partial_collectives().inc()
                resp.prescale = e0.prescale
                resp.postscale = e0.postscale
                resp.root_rank = e0.root_rank
                resp.compression = e0.compression
                hp: List[Tuple[int, int]] = []
                for k in bucket:
                    hp.extend(singles[k][2])
                responses.append(resp)
                handle_pairs.append(hp)
            return (responses, handle_pairs, join_released, last_joined,
                    stall_warnings, stall_shutdown)

    def shutdown(self) -> List[int]:
        with self._lock:
            if self._shutdown:
                return []
            self._shutdown = True
            orphans = [m.handle for st in self._table.values()
                       for m in st.values()]
            orphans.extend(self._join_handles.values())
            self._table.clear()
            self._order.clear()
            self._join_handles.clear()
            self._joined.clear()
        self._timeline.close()
        return orphans

    # ---- timeline / autotune
    def timeline_op_start(self, tensor: str, op: str) -> None:
        self._timeline.op_start(tensor, op)

    def timeline_activity(self, tensor: str, activity: str) -> None:
        self._timeline.activity(tensor, activity)

    def timeline_op_end(self, tensor: str) -> None:
        self._timeline.op_end(tensor)

    def timeline_cycle(self) -> None:
        self._timeline.cycle_tick()

    def timeline_cache(self, hits: int, misses: int) -> None:
        self._timeline.cache_counter(hits, misses)

    def report_score(self, nbytes: int, seconds: float) -> bool:
        return False  # autotune is a native-core feature

    def fusion_threshold(self) -> int:
        return self._threshold

    def cycle_time_ms(self) -> float:
        return self._cycle_ms

    def cache_stats(self):
        return (0, 0)
