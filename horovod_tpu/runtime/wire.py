"""Python codec for the native engine's wire format.

Mirrors `horovod_tpu/_core/wire.h` (the TPU-native replacement for the
reference's FlatBuffers `wire/message.fbs`): little-endian, length-prefixed.
Used to decode tick payloads from the C++ controller and to exchange
request/response lists over the cross-process control plane.
"""

from __future__ import annotations

import struct
from typing import List, Tuple

from .messages import Response, ResponseType


class Reader:
    __slots__ = ("buf", "off")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.off = 0

    def u8(self) -> int:
        v = self.buf[self.off]
        self.off += 1
        return v

    def u32(self) -> int:
        v = struct.unpack_from("<I", self.buf, self.off)[0]
        self.off += 4
        return v

    def i32(self) -> int:
        v = struct.unpack_from("<i", self.buf, self.off)[0]
        self.off += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from("<q", self.buf, self.off)[0]
        self.off += 8
        return v

    def f64(self) -> float:
        v = struct.unpack_from("<d", self.buf, self.off)[0]
        self.off += 8
        return v

    def str(self) -> str:
        n = self.u32()
        v = self.buf[self.off:self.off + n].decode("utf-8")
        self.off += n
        return v


def decode_response(rd: Reader) -> Response:
    rtype = ResponseType(rd.i32())
    names = [rd.str() for _ in range(rd.u32())]
    err = rd.str()
    average = rd.u8() != 0
    prescale = rd.f64()
    postscale = rd.f64()
    root_rank = rd.i32()
    resp = Response(rtype, names, error_message=err, average=average)
    resp.prescale = prescale
    resp.postscale = postscale
    resp.root_rank = root_rank
    return resp


def decode_tick(buf: bytes):
    """Decode one hvd_core_tick payload.

    Returns (responses, handle_pairs_per_response, join_released,
    last_joined, stall_warnings, stall_shutdown).
    """
    rd = Reader(buf)
    n = rd.u32()
    responses = [decode_response(rd) for _ in range(n)]
    handle_pairs: List[List[Tuple[int, int]]] = []
    for _ in range(n):
        m = rd.u32()
        handle_pairs.append([(rd.i32(), rd.i64()) for _ in range(m)])
    join_released = [rd.i64() for _ in range(rd.u32())]
    last_joined = rd.i32()
    stall_warnings = [rd.str() for _ in range(rd.u32())]
    stall_shutdown = rd.u8() != 0
    return (responses, handle_pairs, join_released, last_joined,
            stall_warnings, stall_shutdown)


def decode_handle_list(buf: bytes) -> List[int]:
    rd = Reader(buf)
    return [rd.i64() for _ in range(rd.u32())]
