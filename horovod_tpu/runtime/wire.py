"""Python codec for the native engine's wire format.

Mirrors `horovod_tpu/_core/wire.h` (the TPU-native replacement for the
reference's FlatBuffers `wire/message.fbs`): little-endian, length-prefixed.
Used to decode tick payloads from the C++ controller and to exchange
request/response lists over the cross-process control plane.

This module also owns the control-plane TCP framing (send_frame/recv_frame):
  frame = u32 payload_len | u8 msg_type | u32 seq | i32 rank |
          [u32 fence_epoch when msg_type has the 0x80 bit set] | u32 crc32 |
          [32-byte HMAC-SHA256 when a job secret is set] | payload
The fencing epoch is an *optional* field flagged by the high bit of
msg_type: frames sent with ``fence=0`` (every job without lease-based
leadership, see docs/fault-tolerance.md) never set the bit and are
byte-identical to the pre-fencing format — golden-hex tests pin this.
Receivers that pass a :class:`FenceGuard` reject frames stamped with a
*lower* epoch than the highest they have seen (a deposed coordinator's
traffic), and learn higher epochs by observation.
The CRC32 covers head+payload and rejects corrupted frames cheaply and
unconditionally (the HMAC authenticates, but only when a secret is set);
payload_len is bounded by ``HOROVOD_FRAME_LIMIT_MB`` so a corrupted length
prefix raises a clear :class:`FrameError` instead of an allocation blowup.
A rejected frame is connection-fatal by design: the stream position is
unknowable after corruption, so "resync" means dropping the connection and
letting the reconnect/replay path (docs/fault-tolerance.md) re-establish a
clean stream.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import socket
import struct
import threading
import zlib
from typing import List, Optional, Tuple

from .. import blackbox as _blackbox
from ..exceptions import ShutdownError
from ..metrics import instruments
from .messages import Frame, Response, ResponseType


class FrameError(ConnectionError):
    """A control-plane frame failed integrity checks (CRC/HMAC mismatch or
    oversized length). Subclasses ConnectionError so every handler that
    survives a peer reset also survives a rejected frame."""


_HEAD = struct.Struct("<BIi")

# High bit of the u8 msg_type flags a trailing u32 fencing epoch after the
# fixed head. The remaining 7 bits bound msg_type values at 127.
FENCE_BIT = 0x80

# Frame-type names for blackbox events (numbers match coordinator.MSG_*).
# The bulk data plane (DATA/DATA_RESP) is excluded: it can run at tensor
# rate and would wash everything else out of the ring.
_FRAME_NAMES = {1: "HELLO", 2: "LIST", 3: "RESP", 4: "BYE", 7: "METRICS",
                8: "HEARTBEAT", 9: "RESUME", 10: "TRACE", 11: "CLOCK",
                12: "CLOCK_RESP", 13: "BLACKBOX", 14: "BATCH",
                15: "BATCH_RESP", 16: "BATCH_HB", 17: "REPL_HELLO",
                18: "SNAPSHOT", 19: "JOURNAL", 20: "SERVE_HELLO",
                21: "SERVE_SUBMIT", 22: "SERVE_RESULT", 23: "SERVE_CANCEL",
                24: "SERVE_DRAIN", 26: "CKPT_MARK",
                27: "CKPT_DONE", 28: "FENCED"}


class FenceError(FrameError):
    """A control-plane frame carried a fencing epoch lower than the highest
    this process has observed: the sender is a deposed coordinator whose
    traffic must be ignored. Connection-fatal like every FrameError."""


class FenceGuard:
    """Tracks the highest fencing epoch observed by this process and rejects
    frames stamped with a lower one. Epoch 0 means "no lease-based
    leadership seen yet" and is never rejected — pre-fencing peers stay
    interoperable by construction."""

    __slots__ = ("_epoch", "_lock", "_rank")

    def __init__(self, epoch: int = 0, rank: int = -1):
        self._epoch = epoch
        self._lock = threading.Lock()
        self._rank = rank

    @property
    def epoch(self) -> int:
        return self._epoch

    def observe(self, epoch: int) -> None:
        """Learn a (possibly) newer epoch — from the lease key, a failover
        probe, or a frame stamped higher than anything seen so far."""
        with self._lock:
            if epoch > self._epoch:
                self._epoch = epoch
                instruments.fencing_epoch().set(float(epoch))

    def admit(self, fence: int, msg_type: int, rank: int) -> None:
        if fence == 0:
            return
        if fence < self._epoch:
            instruments.frames_fenced().inc()
            _blackbox.record(
                _blackbox.K_FENCE, "rank_%d" % self._rank,
                "fenced_frame type=%s from_epoch=%d local_epoch=%d "
                "sender_rank=%d" % (_FRAME_NAMES.get(msg_type, msg_type),
                                    fence, self._epoch, rank),
                rank=self._rank)
            raise FenceError(
                "control-plane frame from fencing epoch %d rejected (this "
                "process has observed epoch %d; the sender is a deposed "
                "coordinator)" % (fence, self._epoch))
        self.observe(fence)


def _frame_limit() -> int:
    v = os.environ.get("HOROVOD_FRAME_LIMIT_MB")
    return (int(float(v)) if v else 1024) << 20


def send_frame(sock: socket.socket, secret: str, msg_type: int, seq: int,
               rank: int, payload: bytes = b"", fence: int = 0) -> None:
    if fence:
        head = _HEAD.pack(msg_type | FENCE_BIT, seq, rank) + struct.pack(
            "<I", fence)
    else:
        # no fencing epoch: byte-identical to the pre-fencing frame format
        head = _HEAD.pack(msg_type, seq, rank)
    crc = struct.pack("<I", zlib.crc32(head + payload) & 0xFFFFFFFF)
    mac = (hmac.new(secret.encode(), head + payload, hashlib.sha256).digest()
           if secret else b"")
    frame = struct.pack("<I", len(payload)) + head + crc + mac + payload
    instruments.control_bytes().labels(direction="sent").inc(len(frame))
    bb = _blackbox.active()
    if bb is not None and msg_type in _FRAME_NAMES:
        bb.record(_blackbox.K_FRAME_TX, _FRAME_NAMES[msg_type],
                  "seq=%d len=%d" % (seq, len(payload)), rank)
    sock.sendall(frame)


def recv_exact(sock: socket.socket, n: int, stop: threading.Event) -> bytes:
    """Loop reads to exactly ``n`` bytes (short reads are normal TCP
    behavior, not an error); raises ConnectionError on EOF mid-frame and
    ShutdownError once ``stop`` is set."""
    buf = b""
    while len(buf) < n:
        if stop.is_set():
            raise ShutdownError("control plane shut down")
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            continue
        if not chunk:
            raise ConnectionError("control-plane peer closed connection")
        buf += chunk
    return buf


def recv_frame(sock: socket.socket, secret: str, stop: threading.Event,
               guard: Optional[FenceGuard] = None) -> Frame:
    n = struct.unpack("<I", recv_exact(sock, 4, stop))[0]
    limit = _frame_limit()
    if n > limit:
        instruments.frames_rejected().inc()
        raise FrameError(
            f"control-plane frame declares {n} payload bytes, over the "
            f"{limit}-byte bound (corrupted length prefix? raise "
            "HOROVOD_FRAME_LIMIT_MB only if frames this large are expected)")
    head = recv_exact(sock, _HEAD.size, stop)
    msg_type, seq, rank = _HEAD.unpack(head)
    fence = 0
    if msg_type & FENCE_BIT:
        fence_bytes = recv_exact(sock, 4, stop)
        head += fence_bytes  # CRC/HMAC cover the fencing epoch too
        fence = struct.unpack("<I", fence_bytes)[0]
        msg_type &= ~FENCE_BIT
    crc = struct.unpack("<I", recv_exact(sock, 4, stop))[0]
    mac = recv_exact(sock, 32, stop) if secret else b""
    payload = recv_exact(sock, n, stop) if n else b""
    if zlib.crc32(head + payload) & 0xFFFFFFFF != crc:
        instruments.frames_rejected().inc()
        raise FrameError("control-plane frame CRC32 mismatch "
                         "(corrupted frame; dropping connection to resync)")
    if secret:
        want = hmac.new(secret.encode(), head + payload,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(mac, want):
            instruments.frames_rejected().inc()
            raise FrameError("control-plane HMAC mismatch")
    instruments.control_bytes().labels(direction="recv").inc(
        8 + len(head) + len(mac) + len(payload))
    bb = _blackbox.active()
    if bb is not None and msg_type in _FRAME_NAMES:
        bb.record(_blackbox.K_FRAME_RX, _FRAME_NAMES[msg_type],
                  "seq=%d len=%d" % (seq, len(payload)), rank)
    if guard is not None:
        guard.admit(fence, msg_type, rank)
    return Frame(msg_type, seq, rank, payload)


def encode_resume(last_acked_seq: int) -> bytes:
    """MSG_RESUME payload: the highest seq whose response this worker has
    fully received, so the coordinator can log/prune its replay cache."""
    return struct.pack("<q", last_acked_seq)


def decode_resume(buf: bytes) -> int:
    return struct.unpack("<q", buf[:8])[0] if len(buf) >= 8 else -1


class Writer:
    """Symmetric encoder (the C++ core has its own in `_core/wire.h`; this one
    serves the Python-owned cross-process control plane)."""

    __slots__ = ("parts",)

    def __init__(self):
        self.parts: List[bytes] = []

    def u8(self, v: int) -> None:
        self.parts.append(struct.pack("<B", v))

    def u32(self, v: int) -> None:
        self.parts.append(struct.pack("<I", v))

    def i32(self, v: int) -> None:
        self.parts.append(struct.pack("<i", v))

    def i64(self, v: int) -> None:
        self.parts.append(struct.pack("<q", v))

    def f64(self, v: float) -> None:
        self.parts.append(struct.pack("<d", v))

    def str(self, s: str) -> None:
        b = s.encode("utf-8")
        self.u32(len(b))
        self.parts.append(b)

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


class Reader:
    __slots__ = ("buf", "off")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.off = 0

    def u8(self) -> int:
        v = self.buf[self.off]
        self.off += 1
        return v

    def u32(self) -> int:
        v = struct.unpack_from("<I", self.buf, self.off)[0]
        self.off += 4
        return v

    def i32(self) -> int:
        v = struct.unpack_from("<i", self.buf, self.off)[0]
        self.off += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from("<q", self.buf, self.off)[0]
        self.off += 8
        return v

    def f64(self) -> float:
        v = struct.unpack_from("<d", self.buf, self.off)[0]
        self.off += 8
        return v

    def remaining(self) -> int:
        """Bytes left — lets decoders treat trailing blocks added by newer
        encoders as optional (older frames simply end sooner)."""
        return len(self.buf) - self.off

    def str(self) -> str:
        n = self.u32()
        v = self.buf[self.off:self.off + n].decode("utf-8")
        self.off += n
        return v


def decode_response(rd: Reader) -> Response:
    rtype = ResponseType(rd.i32())
    names = [rd.str() for _ in range(rd.u32())]
    err = rd.str()
    average = rd.u8() != 0
    prescale = rd.f64()
    postscale = rd.f64()
    root_rank = rd.i32()
    resp = Response(rtype, names, error_message=err, average=average)
    resp.prescale = prescale
    resp.postscale = postscale
    resp.root_rank = root_rank
    return resp


def decode_tick(buf: bytes):
    """Decode one hvd_core_tick payload.

    Returns (responses, handle_pairs_per_response, join_released,
    last_joined, stall_warnings, stall_shutdown).
    """
    rd = Reader(buf)
    n = rd.u32()
    responses = [decode_response(rd) for _ in range(n)]
    handle_pairs: List[List[Tuple[int, int]]] = []
    for _ in range(n):
        m = rd.u32()
        handle_pairs.append([(rd.i32(), rd.i64()) for _ in range(m)])
    join_released = [rd.i64() for _ in range(rd.u32())]
    last_joined = rd.i32()
    stall_warnings = [rd.str() for _ in range(rd.u32())]
    stall_shutdown = rd.u8() != 0
    return (responses, handle_pairs, join_released, last_joined,
            stall_warnings, stall_shutdown)


def decode_handle_list(buf: bytes) -> List[int]:
    rd = Reader(buf)
    return [rd.i64() for _ in range(rd.u32())]


# --------------------------------------------------------------------------
# Cross-process control plane messages (coordinator gather/bcast payloads).
# Parity: the serialized RequestList/ResponseList the reference gathers to
# rank 0 and broadcasts back (`message.cc:143-170` FlatBuffers encode;
# `mpi/mpi_controller.cc:107-161` transport). Layout is this repo's
# little-endian length-prefixed wire format, not FlatBuffers.
# --------------------------------------------------------------------------

class ReqMeta:
    """One rank's request metadata as seen by the coordinator
    (message.h Request)."""

    __slots__ = ("name", "rtype", "dtype", "shape", "root_rank", "average",
                 "prescale", "postscale", "splits", "compression")

    def __init__(self, name: str, rtype: int, dtype: str,
                 shape: Tuple[int, ...], root_rank: int = -1,
                 average: bool = False, prescale: float = 1.0,
                 postscale: float = 1.0, splits=None,
                 compression: str = ""):
        self.name = name
        self.rtype = rtype
        self.dtype = dtype
        self.shape = tuple(shape)
        self.root_rank = root_rank
        self.average = average
        self.prescale = prescale
        self.postscale = postscale
        # ragged alltoall: rows of dim 0 this rank sends to each peer
        # (later-horovod `alltoall(tensor, splits)`); None = equal split
        self.splits = None if splits is None else tuple(int(s)
                                                        for s in splits)
        # requested wire compression ("" = none, "int8", "int8-dcn")
        self.compression = compression

    def sig(self) -> Tuple:
        """Cache signature: everything negotiation depends on
        (`response_cache.h:45-97` keys entries the same way)."""
        return (self.name, self.rtype, self.dtype, self.shape,
                self.root_rank, self.average, self.prescale, self.postscale,
                self.splits, self.compression)


# RequestList flags
REQ_JOIN = 1
# this rank reached a commit boundary (elastic: pending joiners are admitted
# once every current member has committed)
REQ_COMMIT = 2

# ResponseList flags
RESP_SHUTDOWN = 1
RESP_JOIN_RELEASE = 2
# membership epoch bumped (worker lost/admitted): the response carries the new
# epoch + member list instead of collective decisions; controllers must drop
# in-flight work and re-sync (elastic subsystem, docs/elastic.md)
RESP_RANKS_CHANGED = 4

# data_exchange result status (elastic host-wire data plane)
DATA_OK = 0
DATA_RANKS_CHANGED = 1
DATA_ERROR = 2


def encode_request_list(flags: int, cached_ids: List[int],
                        new_reqs: List[ReqMeta],
                        score: Optional[Tuple[int, float]] = None,
                        epoch: int = -1) -> bytes:
    """``score`` is this rank's accumulated autotune sample since its last
    frame: (bytes moved, busy seconds). Carried in the request frame the way
    the reference piggybacks parameter-manager traffic on the coordinator
    exchange rather than adding a side channel. ``epoch`` is the sender's
    membership epoch (-1 = non-elastic job, epoch checks disabled); a stale
    epoch makes the coordinator answer RESP_RANKS_CHANGED instead of queuing
    the frame into a barrier the dead rank set can never complete."""
    w = Writer()
    w.u8(flags)
    w.u32(len(cached_ids))
    for cid in cached_ids:
        w.u32(cid)
    w.u32(len(new_reqs))
    for m in new_reqs:
        w.str(m.name)
        w.i32(m.rtype)
        w.str(m.dtype)
        w.u32(len(m.shape))
        for d in m.shape:
            w.i64(d)
        w.i32(m.root_rank)
        w.u8(int(m.average))
        w.f64(m.prescale)
        w.f64(m.postscale)
        if m.splits is None:
            w.u8(0)
        else:
            w.u8(1)
            w.u32(len(m.splits))
            for s in m.splits:
                w.i64(s)
        w.str(m.compression)
    w.u8(0 if score is None else 1)
    if score is not None:
        w.i64(int(score[0]))
        w.f64(float(score[1]))
    w.i32(epoch)
    return w.getvalue()


def decode_request_list(buf: bytes) -> Tuple[int, List[int], List[ReqMeta],
                                             Optional[Tuple[int, float]],
                                             int]:
    rd = Reader(buf)
    flags = rd.u8()
    cached = [rd.u32() for _ in range(rd.u32())]
    reqs = []
    for _ in range(rd.u32()):
        name = rd.str()
        rtype = rd.i32()
        dtype = rd.str()
        shape = tuple(rd.i64() for _ in range(rd.u32()))
        root = rd.i32()
        avg = rd.u8() != 0
        pre = rd.f64()
        post = rd.f64()
        splits = None
        if rd.u8():
            splits = tuple(rd.i64() for _ in range(rd.u32()))
        compression = rd.str()
        reqs.append(ReqMeta(name, rtype, dtype, shape, root, avg, pre, post,
                            splits=splits, compression=compression))
    score = None
    if rd.remaining() and rd.u8():
        score = (rd.i64(), rd.f64())
    epoch = rd.i32() if rd.remaining() >= 4 else -1
    return flags, cached, reqs, score, epoch


def encode_response_list(flags: int, last_joined: int,
                         responses: List[Response],
                         cache_assignments: List[List[int]],
                         stall_warnings: List[str],
                         shutdown_reason: str = "",
                         tuned: Optional[Tuple] = None,
                         epoch: int = -1,
                         members: Optional[List[int]] = None,
                         invalid_ids: Optional[List[int]] = None,
                         excluded: Optional[List[int]] = None) -> bytes:
    """``cache_assignments[i]`` parallels ``responses[i].tensor_names``:
    coordinator-assigned cache id per tensor (-1 = uncached).
    ``shutdown_reason`` distinguishes a normal end-of-job shutdown (empty)
    from an abnormal abort (stall shutdown, peer loss). ``tuned`` broadcasts
    autotuned (fusion_threshold, cycle_time_ms) so every rank applies the
    same parameters at the same tick. ``epoch``/``members`` carry the
    membership state on RESP_RANKS_CHANGED responses (elastic); -1/None on
    ordinary ticks keeps old decoders byte-compatible. ``invalid_ids`` are
    cache ids submitted this tick that the coordinator no longer recognizes
    (LRU-evicted or stall-invalidated): holders must drop the id and
    resubmit full metadata. ``excluded`` lists ranks the straggler policy
    has marked out of the barrier (runtime/straggler.py); the block is
    written ONLY when non-empty, so with the policy disabled (or simply
    nothing excluded) the frame stays byte-identical to the pre-straggler
    wire — pinned by test_straggler's golden-hex check."""
    w = Writer()
    w.u8(flags)
    w.str(shutdown_reason)
    w.i32(last_joined)
    w.u32(len(responses))
    for resp, cids in zip(responses, cache_assignments):
        w.i32(int(resp.response_type))
        w.u32(len(resp.tensor_names))
        for n in resp.tensor_names:
            w.str(n)
        w.str(resp.error_message)
        w.str(resp.tensor_dtype)
        w.str(resp.compression)
        w.u8(int(resp.average))
        w.f64(resp.prescale)
        w.f64(resp.postscale)
        w.i32(resp.root_rank)
        w.u32(len(resp.tensor_shapes))
        for shp in resp.tensor_shapes:
            w.u32(len(shp))
            for d in shp:
                w.i64(d)
        w.u32(len(resp.tensor_sizes))
        for sizes in resp.tensor_sizes:
            w.u32(len(sizes))
            for d in sizes:
                w.i64(d)
        w.u32(len(cids))
        for cid in cids:
            w.i32(cid)
    w.u32(len(stall_warnings))
    for s in stall_warnings:
        w.str(s)
    # tuned flag byte: 0 = absent, 1 = (threshold, cycle_ms) — byte-
    # identical to the pre-bitwidth wire — 2 adds the autotuned bitwidth
    # cap string (adaptive wire), 3 adds the joint tuner's collective
    # algorithm string on top. Decoders before flag N never see the newer
    # fields because the coordinator only emits N when the field exists,
    # so each absent field keeps the frame byte-identical to its
    # predecessor wire (pinned in test_coord.py).
    has_cap = tuned is not None and len(tuned) > 2 and tuned[2]
    has_algo = has_cap and len(tuned) > 3 and tuned[3]
    w.u8(0 if tuned is None else
         (3 if has_algo else (2 if has_cap else 1)))
    if tuned is not None:
        w.i64(int(tuned[0]))
        w.f64(float(tuned[1]))
        if has_cap:
            w.str(str(tuned[2]))
        if has_algo:
            w.str(str(tuned[3]))
    w.i32(epoch)
    w.u32(0 if members is None else len(members))
    for r in (members or ()):
        w.i32(r)
    w.u32(0 if invalid_ids is None else len(invalid_ids))
    for cid in (invalid_ids or ()):
        w.i32(cid)
    # straggler exclusion: optional trailing block, written only when a rank
    # is actually excluded (same absent-means-absent discipline as the tuned
    # flag byte above; old decoders never see it)
    if excluded:
        w.u32(len(excluded))
        for r in excluded:
            w.i32(r)
    return w.getvalue()


def decode_response_list(buf: bytes):
    rd = Reader(buf)
    flags = rd.u8()
    shutdown_reason = rd.str()
    last_joined = rd.i32()
    responses: List[Response] = []
    assignments: List[List[int]] = []
    for _ in range(rd.u32()):
        rtype = ResponseType(rd.i32())
        names = [rd.str() for _ in range(rd.u32())]
        err = rd.str()
        dtype = rd.str()
        compression = rd.str()
        avg = rd.u8() != 0
        pre = rd.f64()
        post = rd.f64()
        root = rd.i32()
        shapes = []
        for _ in range(rd.u32()):
            shapes.append(tuple(rd.i64() for _ in range(rd.u32())))
        sizes = []
        for _ in range(rd.u32()):
            sizes.append([rd.i64() for _ in range(rd.u32())])
        cids = [rd.i32() for _ in range(rd.u32())]
        resp = Response(rtype, names, error_message=err, average=avg)
        resp.tensor_dtype = dtype
        resp.compression = compression
        resp.prescale = pre
        resp.postscale = post
        resp.root_rank = root
        resp.tensor_shapes = shapes
        resp.tensor_sizes = sizes
        responses.append(resp)
        assignments.append(cids)
    warnings = [rd.str() for _ in range(rd.u32())]
    tuned = None
    if rd.remaining():
        tflag = rd.u8()
        if tflag:
            tuned = (rd.i64(), rd.f64())
            if tflag >= 2:
                tuned = tuned + (rd.str(),)
            if tflag >= 3:
                tuned = tuned + (rd.str(),)
    epoch = rd.i32() if rd.remaining() >= 4 else -1
    members: Optional[List[int]] = None
    if rd.remaining() >= 4:
        members = [rd.i32() for _ in range(rd.u32())]
    invalid_ids: List[int] = []
    if rd.remaining() >= 4:
        invalid_ids = [rd.i32() for _ in range(rd.u32())]
    excluded: List[int] = []
    if rd.remaining() >= 4:
        excluded = [rd.i32() for _ in range(rd.u32())]
    return (flags, last_joined, responses, assignments, warnings,
            shutdown_reason, tuned, epoch, members, invalid_ids, excluded)


# --------------------------------------------------------------------------
# Elastic host-wire data plane (MSG_DATA frames through the coordinator).
# Elastic jobs skip jax.distributed, so cross-process XLA collectives are
# unavailable; allreduce/broadcast payloads instead ride the already-open
# control-plane TCP channel, aggregated per (epoch, dseq) over the current
# member set (docs/elastic.md).
# --------------------------------------------------------------------------

def encode_data_request(epoch: int, dseq: int, op: int, root: int,
                        dtype: str, shape: Tuple[int, ...],
                        payload: bytes) -> bytes:
    w = Writer()
    w.i32(epoch)
    w.i64(dseq)
    w.u8(op)
    w.i32(root)
    w.str(dtype)
    w.u32(len(shape))
    for d in shape:
        w.i64(d)
    w.u32(len(payload))
    w.parts.append(payload)
    return w.getvalue()


def decode_data_request(buf: bytes):
    rd = Reader(buf)
    epoch = rd.i32()
    dseq = rd.i64()
    op = rd.u8()
    root = rd.i32()
    dtype = rd.str()
    shape = tuple(rd.i64() for _ in range(rd.u32()))
    n = rd.u32()
    payload = rd.buf[rd.off:rd.off + n]
    return epoch, dseq, op, root, dtype, shape, payload


# --------------------------------------------------------------------------
# Metrics reports (MSG_METRICS frames): one rank's registry snapshot, shipped
# to the coordinator fire-and-forget every HOROVOD_METRICS_INTERVAL seconds
# and merged into the /metrics endpoint (docs/metrics.md). The payload is the
# plain-dict snapshot shape from metrics.MetricsRegistry.snapshot().
# --------------------------------------------------------------------------

def encode_metrics_report(rank: int, timestamp: float,
                          snapshot: dict) -> bytes:
    w = Writer()
    w.i32(rank)
    w.f64(timestamp)
    w.u32(len(snapshot))
    for name in sorted(snapshot):
        fam = snapshot[name]
        w.str(name)
        w.str(fam["kind"])
        w.str(fam.get("help", ""))
        w.str(fam.get("agg", ""))
        buckets = fam.get("buckets") or ()
        w.u32(len(buckets))
        for b in buckets:
            w.f64(float(b))
        series = fam.get("series", [])
        w.u32(len(series))
        for s in series:
            labels = s.get("labels", {})
            w.u32(len(labels))
            for k in sorted(labels):
                w.str(k)
                w.str(str(labels[k]))
            if fam["kind"] == "histogram":
                counts = s["counts"]
                w.u32(len(counts))
                for c in counts:
                    w.i64(int(c))
                w.f64(float(s["sum"]))
                w.i64(int(s["count"]))
            else:
                w.f64(float(s["value"]))
    return w.getvalue()


def decode_metrics_report(buf: bytes):
    """Returns (rank, timestamp, snapshot)."""
    rd = Reader(buf)
    rank = rd.i32()
    timestamp = rd.f64()
    snapshot = {}
    for _ in range(rd.u32()):
        name = rd.str()
        kind = rd.str()
        help_ = rd.str()
        agg = rd.str()
        buckets = [rd.f64() for _ in range(rd.u32())]
        fam = {"kind": kind, "help": help_, "series": []}
        if agg:
            fam["agg"] = agg
        if buckets:
            fam["buckets"] = buckets
        for _ in range(rd.u32()):
            labels = {}
            for _ in range(rd.u32()):
                k = rd.str()
                labels[k] = rd.str()
            if kind == "histogram":
                counts = [rd.i64() for _ in range(rd.u32())]
                total = rd.f64()
                count = rd.i64()
                fam["series"].append({"labels": labels, "counts": counts,
                                      "sum": total, "count": count})
            else:
                fam["series"].append({"labels": labels, "value": rd.f64()})
        snapshot[name] = fam
    return rank, timestamp, snapshot


# --------------------------------------------------------------------------
# Blackbox dumps (MSG_BLACKBOX frames): one rank's postmortem flight-recorder
# dump, shipped to the coordinator on abnormal exit so rank 0 can assemble
# the bundle even when workers cannot reach HOROVOD_BLACKBOX_DIR themselves
# (docs/observability.md). The document is the already-JSON dump payload —
# this is a once-per-process-lifetime frame, so compactness is irrelevant
# and the JSON round-trips into the bundle untouched.
# --------------------------------------------------------------------------

def encode_blackbox_dump(rank: int, timestamp: float, doc_json: str) -> bytes:
    w = Writer()
    w.i32(rank)
    w.f64(timestamp)
    w.str(doc_json)
    return w.getvalue()


def decode_blackbox_dump(buf: bytes):
    """Returns (rank, timestamp, doc_json)."""
    rd = Reader(buf)
    return rd.i32(), rd.f64(), rd.str()


# --------------------------------------------------------------------------
# Trace span batches (MSG_TRACE frames): completed collective-lifecycle spans
# drained from a worker's ring buffer, shipped fire-and-forget like metrics
# reports and merged by rank 0 into one Chrome trace (docs/tracing.md). The
# clock-probe payloads (MSG_CLOCK / MSG_CLOCK_RESP) carry the NTP-style
# offset handshake that aligns every rank's trace timebase to rank 0's.
# --------------------------------------------------------------------------

def encode_trace_batch(rank: int, spans) -> bytes:
    from ..tracing.spans import NUM_TS
    w = Writer()
    w.i32(rank)
    w.u32(len(spans))
    for s in spans:
        w.u8(s.kind)
        w.i32(s.rank)
        w.str(s.name)
        w.str(s.op)
        w.i64(s.span_id)
        w.i64(s.nbytes)
        w.i32(s.fused)
        for i in range(NUM_TS):
            w.i64(s.ts[i])
    return w.getvalue()


def decode_trace_batch(buf: bytes):
    """Returns (sender_rank, [Span])."""
    from ..tracing.spans import NUM_TS, Span
    rd = Reader(buf)
    sender = rd.i32()
    spans = []
    for _ in range(rd.u32()):
        kind = rd.u8()
        rank = rd.i32()
        name = rd.str()
        op = rd.str()
        span_id = rd.i64()
        nbytes = rd.i64()
        fused = rd.i32()
        ts = [rd.i64() for _ in range(NUM_TS)]
        spans.append(Span(kind, rank, name, op=op, span_id=span_id,
                          nbytes=nbytes, fused=fused, ts=ts))
    return sender, spans


def encode_clock_probe(t_local_us: int) -> bytes:
    return struct.pack("<q", t_local_us)


def decode_clock_probe(buf: bytes) -> int:
    return struct.unpack("<q", buf[:8])[0] if len(buf) >= 8 else 0


def encode_clock_reply(server_trace_us: int, trace_id: int) -> bytes:
    return struct.pack("<qq", server_trace_us, trace_id)


def decode_clock_reply(buf: bytes):
    """Returns (server_trace_us, trace_id)."""
    if len(buf) >= 16:
        return struct.unpack("<qq", buf[:16])
    return 0, 0


def encode_data_result(status: int, epoch: int, nparticipants: int,
                       members: Optional[List[int]],
                       payload: bytes) -> bytes:
    """``nparticipants`` lets the sender divide an averaged allreduce by the
    actual member count of the epoch (world size is dynamic under elastic);
    ``members`` rides along on DATA_RANKS_CHANGED so the client can realign
    without an extra round trip."""
    w = Writer()
    w.u8(status)
    w.i32(epoch)
    w.u32(nparticipants)
    w.u32(0 if members is None else len(members))
    for r in (members or ()):
        w.i32(r)
    w.u32(len(payload))
    w.parts.append(payload)
    return w.getvalue()


def decode_data_result(buf: bytes):
    rd = Reader(buf)
    status = rd.u8()
    epoch = rd.i32()
    nparticipants = rd.u32()
    members = [rd.i32() for _ in range(rd.u32())]
    n = rd.u32()
    payload = rd.buf[rd.off:rd.off + n]
    return status, epoch, nparticipants, members, payload


# --------------------------------------------------------------------------
# Hierarchical control plane (MSG_BATCH / MSG_BATCH_RESP / MSG_BATCH_HB).
# A per-host sub-coordinator aggregates its local ranks' negotiation frames
# and ships ONE batched frame per round to rank 0, which answers with one
# batched response — rank 0 does O(hosts) frame work per round instead of
# O(ranks) (docs/control-plane.md). Entries are opaque (rank, seq, payload)
# triples: the inner payloads are ordinary request/response-list bytes, so
# the batch layer composes with every existing codec unchanged.
# --------------------------------------------------------------------------

def encode_batched_entries(entries: List[Tuple[int, int, bytes]]) -> bytes:
    """Shared layout for MSG_BATCH and MSG_BATCH_RESP:
    [(rank, seq, inner_payload)]."""
    w = Writer()
    w.u32(len(entries))
    for rank, seq, payload in entries:
        w.i32(rank)
        w.u32(seq)
        w.u32(len(payload))
        w.parts.append(payload)
    return w.getvalue()


def decode_batched_entries(buf: bytes) -> List[Tuple[int, int, bytes]]:
    rd = Reader(buf)
    entries = []
    for _ in range(rd.u32()):
        rank = rd.i32()
        seq = rd.u32()
        n = rd.u32()
        entries.append((rank, seq, rd.buf[rd.off:rd.off + n]))
        rd.off += n
    return entries


def encode_batched_heartbeat(ranks: List[int]) -> bytes:
    """MSG_BATCH_HB: every listed local rank is alive as of this frame."""
    w = Writer()
    w.u32(len(ranks))
    for r in ranks:
        w.i32(r)
    return w.getvalue()


def decode_batched_heartbeat(buf: bytes) -> List[int]:
    rd = Reader(buf)
    return [rd.i32() for _ in range(rd.u32())]


# --------------------------------------------------------------------------
# Coordinator replication stream (MSG_REPL_HELLO / MSG_SNAPSHOT /
# MSG_JOURNAL). A warm-standby coordinator dials rank 0, identifies itself
# with REPL_HELLO, receives one SNAPSHOT of the membership state, then a
# JOURNAL record per epoch change. Collective negotiation state is NOT
# replicated: promotion always bumps the epoch (rank 0 was a member and
# just died), which makes every worker drop in-flight negotiation and
# re-sync from its elastic commit — so membership is the only durable
# state (docs/control-plane.md).
# --------------------------------------------------------------------------

def encode_coord_snapshot(jseq: int, epoch: int, world: int, elastic: bool,
                          members: List[int], next_cache_id: int) -> bytes:
    w = Writer()
    w.i64(jseq)
    w.i32(epoch)
    w.i32(world)
    w.u8(int(elastic))
    w.u32(len(members))
    for r in members:
        w.i32(r)
    w.i32(next_cache_id)
    return w.getvalue()


def decode_coord_snapshot(buf: bytes):
    """Returns (jseq, epoch, world, elastic, members, next_cache_id)."""
    rd = Reader(buf)
    jseq = rd.i64()
    epoch = rd.i32()
    world = rd.i32()
    elastic = rd.u8() != 0
    members = [rd.i32() for _ in range(rd.u32())]
    next_cache_id = rd.i32()
    return jseq, epoch, world, elastic, members, next_cache_id


def encode_coord_journal(jseq: int, epoch: int, members: List[int],
                         reason: str, subtree: str = "") -> bytes:
    w = Writer()
    w.i64(jseq)
    w.i32(epoch)
    w.u32(len(members))
    for r in members:
        w.i32(r)
    w.str(reason)
    if subtree:
        # trailing optional block: old decoders stop before it, old frames
        # simply end sooner for the tagged decoder below
        w.str(subtree)
    return w.getvalue()


def decode_coord_journal(buf: bytes):
    """Returns (jseq, epoch, members, reason)."""
    rd = Reader(buf)
    jseq = rd.i64()
    epoch = rd.i32()
    members = [rd.i32() for _ in range(rd.u32())]
    reason = rd.str()
    return jseq, epoch, members, reason


def decode_coord_journal_tagged(buf: bytes):
    """Returns (jseq, epoch, members, reason, subtree).

    ``subtree`` names the aggregation subtree whose churn produced this
    record ("t{tier}.{index}") or "" for a whole-job record — the key a
    tier-scoped standby filters its journal shard by."""
    rd = Reader(buf)
    jseq = rd.i64()
    epoch = rd.i32()
    members = [rd.i32() for _ in range(rd.u32())]
    reason = rd.str()
    subtree = rd.str() if rd.remaining() else ""
    return jseq, epoch, members, reason, subtree


# --------------------------------------------------------------------------
# N-tier hierarchical batch frames (MSG_TBATCH / MSG_TBATCH_RESP /
# MSG_THB). Above one host tier, per-rank batch entries stop scaling: a
# pod-level aggregator fronting 100k ranks would re-ship 100k (rank, seq,
# payload) triples upstream every round. Tier frames instead carry GROUPS —
# one (seq, payload, runs) per distinct payload, where ``runs`` is a
# run-length list [(start_rank, count), ...] naming every rank that
# submitted those exact bytes. In steady state all ranks request the same
# tensors, so a whole subtree collapses to one group and rank-0 work per
# round is O(direct children), not O(ranks) (docs/control-plane.md).
# --------------------------------------------------------------------------

Runs = List[Tuple[int, int]]


def ranks_to_runs(ranks) -> Runs:
    """Compress a rank collection to sorted [(start, count)] runs."""
    out: Runs = []
    for r in sorted(ranks):
        if out and out[-1][0] + out[-1][1] == r:
            out[-1] = (out[-1][0], out[-1][1] + 1)
        else:
            out.append((r, 1))
    return out


def runs_to_ranks(runs: Runs) -> List[int]:
    return [r for start, count in runs for r in range(start, start + count)]


def runs_count(runs: Runs) -> int:
    return sum(count for _, count in runs)


def runs_contain(runs: Runs, rank: int) -> bool:
    return any(start <= rank < start + count for start, count in runs)


def merge_runs(a: Runs, b: Runs) -> Runs:
    """Union of two disjoint run lists, coalescing adjacency — the O(runs)
    step a mid-tier aggregator does instead of touching per-rank state."""
    out: Runs = []
    ia = ib = 0
    while ia < len(a) or ib < len(b):
        if ib >= len(b) or (ia < len(a) and a[ia][0] <= b[ib][0]):
            start, count = a[ia]
            ia += 1
        else:
            start, count = b[ib]
            ib += 1
        if out and out[-1][0] + out[-1][1] == start:
            out[-1] = (out[-1][0], out[-1][1] + count)
        else:
            out.append((start, count))
    return out


def runs_intersect(a: Runs, b: Runs) -> Runs:
    out: Runs = []
    ia = ib = 0
    while ia < len(a) and ib < len(b):
        lo = max(a[ia][0], b[ib][0])
        hi = min(a[ia][0] + a[ia][1], b[ib][0] + b[ib][1])
        if lo < hi:
            out.append((lo, hi - lo))
        if a[ia][0] + a[ia][1] <= b[ib][0] + b[ib][1]:
            ia += 1
        else:
            ib += 1
    return out


def runs_subtract(a: Runs, b: Runs) -> Runs:
    """Ranks in ``a`` but not ``b`` — what stays in a relay's in-flight
    ledger when a response covers only part of a shipped group."""
    out: Runs = []
    ib = 0
    for start, count in a:
        lo, hi = start, start + count
        while lo < hi:
            while ib < len(b) and b[ib][0] + b[ib][1] <= lo:
                ib += 1
            if ib >= len(b) or b[ib][0] >= hi:
                out.append((lo, hi - lo))
                break
            if b[ib][0] > lo:
                out.append((lo, b[ib][0] - lo))
            lo = b[ib][0] + b[ib][1]
        # rewind not needed: both lists are sorted and disjoint
    return out


def _write_runs(w: Writer, runs: Runs) -> None:
    w.u32(len(runs))
    for start, count in runs:
        w.i32(start)
        w.u32(count)


def _read_runs(rd: Reader) -> Runs:
    return [(rd.i32(), rd.u32()) for _ in range(rd.u32())]


def encode_tier_batch(tier: int, index: int,
                      groups: List[Tuple[int, bytes, Runs]]) -> bytes:
    """MSG_TBATCH: [(seq, inner_payload, runs)] from tier aggregator
    (tier, index); every rank in ``runs`` submitted exactly ``payload``."""
    w = Writer()
    w.u8(tier)
    w.u32(index)
    w.u32(len(groups))
    for seq, payload, runs in groups:
        w.u32(seq)
        w.u32(len(payload))
        w.parts.append(payload)
        _write_runs(w, runs)
    return w.getvalue()


def decode_tier_batch(buf: bytes):
    """Returns (tier, index, [(seq, payload, runs)])."""
    rd = Reader(buf)
    tier = rd.u8()
    index = rd.u32()
    groups = []
    for _ in range(rd.u32()):
        seq = rd.u32()
        n = rd.u32()
        payload = rd.buf[rd.off:rd.off + n]
        rd.off += n
        groups.append((seq, payload, _read_runs(rd)))
    return tier, index, groups


def encode_tier_batch_resp(groups: List[Tuple[int, bytes, Runs]]) -> bytes:
    """MSG_TBATCH_RESP: [(seq, response_bytes, runs)] — one response per
    request group, echoing the runs it covers for downstream routing."""
    w = Writer()
    w.u32(len(groups))
    for seq, payload, runs in groups:
        w.u32(seq)
        w.u32(len(payload))
        w.parts.append(payload)
        _write_runs(w, runs)
    return w.getvalue()


def decode_tier_batch_resp(buf: bytes):
    rd = Reader(buf)
    groups = []
    for _ in range(rd.u32()):
        seq = rd.u32()
        n = rd.u32()
        payload = rd.buf[rd.off:rd.off + n]
        rd.off += n
        groups.append((seq, payload, _read_runs(rd)))
    return groups


def encode_tier_heartbeat(tier: int, index: int, runs: Runs) -> bytes:
    """MSG_THB: every rank in ``runs`` is vouched alive by aggregator
    (tier, index) — the run-length form of MSG_BATCH_HB."""
    w = Writer()
    w.u8(tier)
    w.u32(index)
    _write_runs(w, runs)
    return w.getvalue()


def decode_tier_heartbeat(buf: bytes):
    rd = Reader(buf)
    return rd.u8(), rd.u32(), _read_runs(rd)


# --------------------------------------------------------------------------
# Inference serving frames (MSG_SERVE_HELLO / MSG_SERVE_SUBMIT /
# MSG_SERVE_RESULT). The serving frontend speaks the SAME hardened framing
# as the training control plane — CRC/HMAC, frame-size bounds, heartbeats
# (MSG_HEARTBEAT rides unchanged), reconnect-and-resubmit recovery — so
# the PR-4 integrity and liveness machinery protects request traffic for
# free (serving/server.py, docs/inference.md). SUBMIT flows client ->
# frontend -> worker replica; RESULT flows back. Request ids are
# client-chosen strings: the frontend dedupes on them, which is what makes
# resubmit-after-reconnect exactly-once from the client's point of view.
# --------------------------------------------------------------------------

MSG_SERVE_HELLO = 20
MSG_SERVE_SUBMIT = 21
MSG_SERVE_RESULT = 22
# Cancellation/drain (docs/inference.md failure matrix). CANCEL flows both
# directions: client -> frontend (deadline expiry, abandoned request) and
# frontend -> worker (propagating the cancel, deadline sweep, hedging
# loser). DRAIN flows frontend -> worker only and quiesces the replica:
# finish in-flight, hand queued work back as SERVE_REJECTED, refuse new.
MSG_SERVE_CANCEL = 23
MSG_SERVE_DRAIN = 24

# MSG_SERVE_HELLO roles
SERVE_ROLE_CLIENT = 0
SERVE_ROLE_WORKER = 1

# MSG_SERVE_RESULT statuses
SERVE_OK = 0          # tokens carry the completed generation
SERVE_FAILED = 1      # non-retryable (bad request / engine error)
SERVE_REJECTED = 2    # admission backpressure — retry with backoff
SERVE_CANCELLED = 3   # terminal: cancelled (deadline / client abandon)
SERVE_SHED = 4        # terminal: shed by overload admission control

# MSG_SERVE_SUBMIT priority classes (the trailing optional block below).
SERVE_PRIO_HIGH = 0         # interactive traffic — never shed
SERVE_PRIO_BEST_EFFORT = 1  # browned out, then shed, under overload
SERVE_CLASS_NAMES = {SERVE_PRIO_HIGH: "high",
                     SERVE_PRIO_BEST_EFFORT: "best_effort"}


def encode_serve_hello(role: int, name: str, capacity: int) -> bytes:
    """``capacity``: a worker's decode-batch width (its max concurrent
    requests, the dispatcher's load-balancing weight); 0 for clients."""
    w = Writer()
    w.u8(role)
    w.str(name)
    w.u32(capacity)
    return w.getvalue()


def decode_serve_hello(buf: bytes):
    """Returns (role, name, capacity)."""
    rd = Reader(buf)
    return rd.u8(), rd.str(), rd.u32()


def encode_serve_submit(request_id: str, prompt: List[int],
                        max_new_tokens: int, eos_id: Optional[int],
                        deadline: float = 0.0, priority: int = 0) -> bytes:
    """``deadline`` is a *relative* budget in seconds (0.0 = none; each hop
    re-anchors it on its own clock, so no cross-host clock comparison),
    ``priority`` a SERVE_PRIO_* class. Both ride an optional trailing block
    written only when non-default, so knobs-unset frames stay byte-identical
    to the pre-robustness format (same discipline as the coordinator
    journal's trailing subtree field)."""
    w = Writer()
    w.str(request_id)
    w.u32(len(prompt))
    for t in prompt:
        w.i32(int(t))
    w.u32(max_new_tokens)
    w.i32(-1 if eos_id is None else int(eos_id))
    if deadline != 0.0 or priority != 0:
        w.f64(deadline)
        w.u8(priority)
    return w.getvalue()


def decode_serve_submit(buf: bytes):
    """Returns (request_id, prompt, max_new_tokens, eos_id|None)."""
    return decode_serve_submit_ex(buf)[:4]


def decode_serve_submit_ex(buf: bytes):
    """Returns (request_id, prompt, max_new_tokens, eos_id|None, deadline,
    priority) — deadline 0.0 / priority SERVE_PRIO_HIGH when the sender
    wrote the legacy 4-field frame."""
    rd = Reader(buf)
    request_id = rd.str()
    prompt = [rd.i32() for _ in range(rd.u32())]
    max_new = rd.u32()
    eos = rd.i32()
    deadline, priority = 0.0, SERVE_PRIO_HIGH
    if rd.remaining():
        deadline = rd.f64()
        priority = rd.u8()
    return (request_id, prompt, max_new, (None if eos < 0 else eos),
            deadline, priority)


def encode_serve_result(request_id: str, status: int, tokens: List[int],
                        error: str = "", latency: float = 0.0) -> bytes:
    w = Writer()
    w.str(request_id)
    w.u8(status)
    w.u32(len(tokens))
    for t in tokens:
        w.i32(int(t))
    w.str(error)
    w.f64(latency)
    return w.getvalue()


def decode_serve_result(buf: bytes):
    """Returns (request_id, status, tokens, error, latency)."""
    rd = Reader(buf)
    request_id = rd.str()
    status = rd.u8()
    tokens = [rd.i32() for _ in range(rd.u32())]
    error = rd.str()
    latency = rd.f64()
    return request_id, status, tokens, error, latency


def encode_serve_cancel(request_id: str, reason: str = "") -> bytes:
    w = Writer()
    w.str(request_id)
    w.str(reason)
    return w.getvalue()


def decode_serve_cancel(buf: bytes):
    """Returns (request_id, reason)."""
    rd = Reader(buf)
    return rd.str(), rd.str()


def encode_serve_drain(reason: str = "") -> bytes:
    w = Writer()
    w.str(reason)
    return w.getvalue()


def decode_serve_drain(buf: bytes) -> str:
    return Reader(buf).str()


# Frontend warm-standby replication (docs/inference.md). The standby dials
# the active frontend with MSG_REPL_HELLO payload b"serve" and receives the
# frontend's durable request state over the SAME MSG_SNAPSHOT/MSG_JOURNAL
# framing the coordinator standby and the checkpoint buddy plane use: one
# snapshot (the result dedupe LRU + every open request's submit payload),
# then one journal record per state change. That state is exactly what
# exactly-once delivery needs to survive a frontend SIGKILL — open requests
# are re-dispatched by the promoted standby, completed ones answered from
# the replicated LRU instead of re-running.

SERVE_J_SUBMIT = 0   # blob = the accepted MSG_SERVE_SUBMIT payload
SERVE_J_RESULT = 1   # blob = the terminal MSG_SERVE_RESULT payload
SERVE_J_CANCEL = 2   # blob = the MSG_SERVE_CANCEL payload


def encode_serve_snapshot(epoch: int, results: List[bytes],
                          pending: List[bytes]) -> bytes:
    """``results``: encoded MSG_SERVE_RESULT payloads (the dedupe LRU, in
    insertion order); ``pending``: encoded MSG_SERVE_SUBMIT payloads for
    every request not yet terminally answered."""
    w = Writer()
    w.u32(epoch)
    w.u32(len(results))
    for blob in results:
        _put_bytes(w, blob)
    w.u32(len(pending))
    for blob in pending:
        _put_bytes(w, blob)
    return w.getvalue()


def decode_serve_snapshot(buf: bytes):
    """Returns (epoch, results, pending)."""
    rd = Reader(buf)
    epoch = rd.u32()
    results = [_get_bytes(rd) for _ in range(rd.u32())]
    pending = [_get_bytes(rd) for _ in range(rd.u32())]
    return epoch, results, pending


def encode_serve_journal(kind: int, blob: bytes) -> bytes:
    w = Writer()
    w.u8(kind)
    _put_bytes(w, blob)
    return w.getvalue()


def decode_serve_journal(buf: bytes):
    """Returns (kind, blob) — kind is a SERVE_J_* tag."""
    rd = Reader(buf)
    return rd.u8(), _get_bytes(rd)


# --------------------------------------------------------------------------
# Async sharded checkpointing (MSG_CKPT_MARK / MSG_CKPT_DONE, ids 26/27,
# docs/checkpoint.md). Both directions are fire-and-forget off the step
# path: a rank announces the step it is snapshotting with CKPT_MARK, then
# reports its shard landed on disk with CKPT_DONE; the coordinator stamps
# the membership epoch on the MARK and finalizes the bundle manifest only
# when every member shard of the SAME step has reported DONE. Frames are
# sent only when HOROVOD_CKPT_DIR is set, so knobs-unset jobs keep a
# byte-identical wire.
#
# The buddy-journal stream between shard peers reuses the standby
# replication framing (MSG_REPL_HELLO / MSG_SNAPSHOT / MSG_JOURNAL frame
# types) with the shard payloads below; the hello payload distinguishes a
# pushing owner ("push:{index}") from a fetching replacement
# ("fetch:{index}").
# --------------------------------------------------------------------------

MSG_CKPT_MARK = 26
MSG_CKPT_DONE = 27


def _put_bytes(w: Writer, b: bytes) -> None:
    w.u32(len(b))
    w.parts.append(bytes(b))


def _get_bytes(rd: Reader) -> bytes:
    n = rd.u32()
    v = rd.buf[rd.off:rd.off + n]
    rd.off += n
    return v


def encode_ckpt_mark(step: int, epoch: int, index: int) -> bytes:
    """A rank began double-buffering its shard for ``step`` under the
    membership ``epoch`` it observed; ``index`` is its shard slot (its
    position in the sorted member set)."""
    w = Writer()
    w.i64(step)
    w.i32(epoch)
    w.i32(index)
    return w.getvalue()


def decode_ckpt_mark(buf: bytes):
    """Returns (step, epoch, index)."""
    rd = Reader(buf)
    return rd.i64(), rd.i32(), rd.i32()


def encode_ckpt_done(step: int, epoch: int, index: int, nbytes: int,
                     crc: int) -> bytes:
    """The rank's ``step`` shard file landed on disk: ``nbytes`` written,
    CRC32 ``crc`` — the manifest row the coordinator records."""
    w = Writer()
    w.i64(step)
    w.i32(epoch)
    w.i32(index)
    w.i64(nbytes)
    w.u32(crc & 0xFFFFFFFF)
    return w.getvalue()


def decode_ckpt_done(buf: bytes):
    """Returns (step, epoch, index, nbytes, crc)."""
    rd = Reader(buf)
    return rd.i64(), rd.i32(), rd.i32(), rd.i64(), rd.u32()


def encode_shard_snapshot(index: int, step: int, data: bytes) -> bytes:
    """Buddy-journal full-shard payload (rides MSG_SNAPSHOT): the complete
    shard bytes for slot ``index`` as of committed ``step``."""
    w = Writer()
    w.i32(index)
    w.i64(step)
    _put_bytes(w, data)
    return w.getvalue()


def decode_shard_snapshot(buf: bytes):
    """Returns (index, step, data)."""
    rd = Reader(buf)
    return rd.i32(), rd.i64(), _get_bytes(rd)


def encode_shard_journal(index: int, step: int, total_len: int,
                         blocks) -> bytes:
    """Buddy-journal delta payload (rides MSG_JOURNAL): the byte ranges of
    slot ``index``'s shard that changed since the last push, as
    ``(offset, bytes)`` blocks over a shard now ``total_len`` long."""
    w = Writer()
    w.i32(index)
    w.i64(step)
    w.i64(total_len)
    w.u32(len(blocks))
    for off, data in blocks:
        w.i64(off)
        _put_bytes(w, data)
    return w.getvalue()


def decode_shard_journal(buf: bytes):
    """Returns (index, step, total_len, blocks)."""
    rd = Reader(buf)
    index = rd.i32()
    step = rd.i64()
    total_len = rd.i64()
    blocks = [(rd.i64(), _get_bytes(rd)) for _ in range(rd.u32())]
    return index, step, total_len, blocks
