"""Cross-process control plane: coordinator service + coordinated controller.

Reference parity: ``Controller::ComputeResponseList``
(`horovod/common/controller.cc:55-336`) with the MPI transport
(`horovod/common/mpi/mpi_controller.cc:107-161`: Gatherv serialized
RequestLists to rank 0, Bcast the ResponseList back) re-expressed TPU-natively.
Ranks are ``jax.distributed`` processes; the gather/bcast rides a
persistent-TCP coordinator service hosted inside rank 0's process (there is no
MPI on TPU — XLA collectives are the data plane only, so the control plane
needs its own host-side transport). The negotiated ResponseList gives every
process an IDENTICAL execution order for the multi-controller XLA programs —
the TPU analogue of the reference's guarantee that all ranks execute the same
fused response in the same tick.

What negotiation provides over the round-1 "SPMD program order" mode:
  * cross-rank validation (shape/dtype/op mismatch -> coordinated ERROR with
    per-rank detail, `controller.cc:358-597` ConstructResponse);
  * tensor fusion whose buckets cannot diverge across processes
    (`controller.cc:626-750` FuseResponses);
  * ragged allgather (per-rank dim0 negotiation, Response::tensor_sizes);
  * join with zero contributions (`controller.cc:202-256`);
  * cross-rank stall detection (a rank that never submits is visible at the
    coordinator, `stall_inspector.{h,cc}`);
  * the response-cache fast path (`response_cache.{h,cc}`, fast path
    `controller.cc:171-185`): first negotiation of a tensor assigns a cache
    id; steady-state ticks submit 4-byte ids instead of full request metadata
    and skip re-validation at the coordinator.

Wire protocol: framed over one persistent TCP connection per worker
(CRC32-checked, size-bounded framing owned by `runtime/wire.py`
send_frame/recv_frame). Payloads are the RequestList/ResponseList codecs in
`runtime/wire.py`. Address discovery: rank 0 binds an ephemeral port and
publishes it through the launcher's HMAC KV store
(``HVD_KV_ADDR``/``HVD_SECRET``) or, absent a launcher, through the
jax.distributed coordinator's KV service.

Fault tolerance (docs/fault-tolerance.md): a dropped worker connection is no
longer fatal. Workers reconnect with bounded exponential backoff and replay
the in-flight request under its original ``seq``; the coordinator caches the
last response per rank so a replay is answered idempotently instead of
double-applying the request list. The coordinator declares a rank dead only
after ``HOROVOD_RECONNECT_GRACE`` passes with no resume (or, for silent
deaths where TCP never errors, after ``HOROVOD_HEARTBEAT_TIMEOUT`` with no
frame), feeding the existing elastic ``rank_lost`` path.
"""

from __future__ import annotations

import logging
import os
import re
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from collections import OrderedDict

from ..exceptions import RanksChangedError, ShutdownError, WorkerLostError
from ..metrics import instruments
from ..utils.env import env_float as _env_float
from ..utils.timeline import Timeline
from .. import blackbox as _blackbox
from .. import faultinject
from .messages import RequestType, Response, ResponseType, TensorTableEntry
from . import straggler as straggler_mod
from . import wire
from .wire import ReqMeta

logger = logging.getLogger("horovod_tpu")

MSG_HELLO = 1
MSG_LIST = 2
MSG_RESP = 3
MSG_BYE = 4
# elastic host-wire data plane: allreduce/broadcast payload riding the
# control-plane channel (elastic jobs have no cross-process XLA collectives)
MSG_DATA = 5
MSG_DATA_RESP = 6
# fire-and-forget metrics report (rank registry snapshot -> coordinator); no
# reply frame is sent, so it is safe to interleave with MSG_LIST/MSG_DATA
# exchanges (their recv loops skip non-matching frame types)
MSG_METRICS = 7
# fire-and-forget worker liveness beacon, sent off-thread every
# HOROVOD_HEARTBEAT_INTERVAL seconds so a worker stuck in a long compile (or
# simply idle) still proves it is alive
MSG_HEARTBEAT = 8
# hello variant announcing a reconnect: payload carries the last seq whose
# response the worker fully received; the serve loop answers replayed
# requests from the coordinator's per-rank response cache
MSG_RESUME = 9
# fire-and-forget trace-span batch (worker ring-buffer drain -> rank 0's
# merged Chrome trace, docs/tracing.md); same interleaving contract as
# MSG_METRICS
MSG_TRACE = 10
# trace clock handshake: worker sends its local timestamp, rank 0 replies
# with its own trace clock + the job's trace id; the worker derives a
# min-RTT NTP-style offset so spans from every rank share one timeline
MSG_CLOCK = 11
MSG_CLOCK_RESP = 12
# fire-and-forget postmortem dump (a worker's flight-recorder JSON doc ->
# rank 0, which persists it into the blackbox bundle, docs/observability.md);
# same interleaving contract as MSG_METRICS
MSG_BLACKBOX = 13
# hierarchical control plane (HOROVOD_HIERARCHICAL_COORD,
# docs/control-plane.md): a per-host sub-coordinator ships its local ranks'
# negotiation frames as ONE batched frame per round, and rank 0 answers with
# batched responses — possibly several per request frame, since joiner
# admissions complete later than member barriers (entries self-identify by
# (rank, seq), so response frames need no 1:1 pairing with request frames)
MSG_BATCH = 14
MSG_BATCH_RESP = 15
# aggregated liveness beacon: every rank listed is alive; ranks that vanish
# from a connection's beacon are treated as disconnected (the sub-coordinator
# observed their local connection die)
MSG_BATCH_HB = 16
# coordinator replication stream (HOROVOD_STANDBY_COORD): the warm standby
# identifies itself with REPL_HELLO and receives one SNAPSHOT of the
# membership state followed by a JOURNAL record per epoch change
MSG_REPL_HELLO = 17
MSG_SNAPSHOT = 18
MSG_JOURNAL = 19
# N-tier hierarchical control plane (HOROVOD_HIERARCHY_TIERS >= 2,
# docs/control-plane.md): tier aggregators ship GROUPED batches — one
# (seq, payload, runs) entry per distinct payload, runs naming the ranks
# that submitted those exact bytes — so rank 0's per-round work is bounded
# by its direct children, not by total ranks. 20-22 are the serving frames
# (wire.MSG_SERVE_*).
MSG_TBATCH = 23
MSG_TBATCH_RESP = 24
MSG_THB = 25
# async sharded checkpointing (HOROVOD_CKPT_DIR, docs/checkpoint.md):
# fire-and-forget consistency stamps — MARK announces a rank snapshotted
# its shard for a step, DONE that the shard file landed on disk; rank 0
# finalizes the bundle manifest once every member of the SAME step is
# done. Codecs live in wire.py (wire.MSG_CKPT_*); no frame exists unless
# the knob is set.
MSG_CKPT_MARK = wire.MSG_CKPT_MARK
MSG_CKPT_DONE = wire.MSG_CKPT_DONE
# Fenced-leadership plane (HOROVOD_LEASE_TTL, docs/fault-tolerance.md): a
# coordinator that lost (or could not renew) the leadership lease answers
# every frame with FENCED — stamped with its last-held fencing epoch — and
# closes the connection. Workers treat it as a lost connection and redial
# (finding the promoted standby via the failover probe); a receiver that
# already follows a higher epoch rejects the frame outright.
MSG_FENCED = 28

# After a membership reset every surviving controller realigns its tick
# counter to epoch * EPOCH_SEQ_BASE so the survivors' next exchanges land on
# a common sequence number regardless of how far each had advanced.
EPOCH_SEQ_BASE = 1 << 20

_FUSABLE = (int(RequestType.ALLREDUCE), int(RequestType.ADASUM),
            int(RequestType.ALLGATHER))


class CoordinatorFencedError(ConnectionError):
    """This coordinator lost its leadership lease: it must not serve.
    Subclasses ConnectionError so worker-facing handlers treat a fenced
    exchange like a dead one (reconnect and find the promoted standby)."""


# ---------------------------------------------------------------- coordinator
class _Pending:
    """Coordinator-side state for one named tensor still being negotiated."""

    __slots__ = ("metas", "first_t", "order_idx", "arrivals", "gcount",
                 "grouped")

    def __init__(self, order_idx: int):
        self.metas: Dict[int, ReqMeta] = {}
        self.first_t = time.monotonic()
        self.order_idx = order_idx
        # first-arrival time per rank: the spread when the tensor becomes
        # ready is the straggler skew (hvd_straggler_skew_seconds)
        self.arrivals: Dict[int, float] = {}
        # grouped tier deposits: gcount ranks vouched via run-length groups
        # (their metas are identical to a stored representative), grouped
        # marks which meta keys are representatives rather than per-rank
        # deposits — the readiness check counts instead of enumerating
        self.gcount = 0
        self.grouped: set = set()


class CoordState:
    """Rank-0 negotiation state machine; one instance per job.

    All methods are driven from per-connection server threads (workers) and
    rank 0's engine thread (direct calls) under one lock — the analogue of the
    single coordinator thread in `controller.cc:55-336`.
    """

    def __init__(self, world: int, fusion_threshold: int,
                 cache_capacity: int, stall_warning_s: float,
                 stall_shutdown_s: float, tuner=None,
                 elastic: bool = False):
        self.world = world
        self.threshold = fusion_threshold
        self.cache_capacity = cache_capacity
        # GP/EI parameter manager (native NativeTuner); scores arrive in
        # request frames, tuned params leave in every rank's ResponseList —
        # the coordinated analogue of the reference controller broadcasting
        # parameter-manager updates to all workers
        self.tuner = tuner
        # bitwidth-cap axis of the autotune search (ops/adaptive.py
        # BitwidthTuner); created lazily on the first adaptive-wire request
        # so non-adaptive jobs keep the exact two-field tuned broadcast
        self.bw_tuner = None
        self.round_bytes = 0
        self.round_seconds = 0.0
        self.tuned: Optional[Tuple] = None
        self.stall_warning_s = stall_warning_s
        self.stall_shutdown_s = stall_shutdown_s
        # enforced watchdog (docs/fault-tolerance.md): 0 keeps the
        # historical warn-only stall inspector
        self.collective_timeout_s = _env_float(
            "HOROVOD_COLLECTIVE_TIMEOUT", 0.0)
        self.cv = threading.Condition()
        self.lists: Dict[int, Dict[int, Tuple[int, List[int], List[ReqMeta]]]] = {}
        self.resps: Dict[int, bytes] = {}
        self.fetched: Dict[int, int] = {}
        self.table: Dict[str, _Pending] = {}
        self.order_ctr = 0
        self.joined: set = set()
        self.last_joined = -1
        self.bye = False
        self.shutdown_reason = ""
        # fenced leadership (HOROVOD_LEASE_TTL, docs/fault-tolerance.md): a
        # coordinator that lost its lease parks here — every exchange
        # raises, every barrier wait releases, and the server answers
        # MSG_FENCED until the process winds down
        self.fenced = False
        self.fence_reason = ""
        # response cache: name -> id (LRU-ordered; least recently touched
        # first) and id -> {rank: that rank's last full ReqMeta}. Per-rank
        # metas keep ragged allgathers cacheable (each rank's dim0 differs);
        # a rank whose request params change simply misses its local sig
        # cache and retransmits, refreshing its meta here. Ids come from a
        # monotonic counter and are NEVER reused: a worker still holding an
        # evicted id must never alias another tensor's metadata, so eviction
        # invalidates (via the ResponseList ``invalid_ids`` block) instead
        # of recycling.
        self.cache_ids: "OrderedDict[str, int]" = OrderedDict()
        self.cache_meta: Dict[int, Dict[int, ReqMeta]] = {}
        self.next_cache_id = 0
        self.cache_hits = 0
        self.cache_misses = 0
        # ---- reconnect/replay (docs/fault-tolerance.md): the last response
        # handed to each rank, keyed by its seq, so a worker that lost the
        # reply mid-flight can reconnect and replay the request without the
        # coordinator double-applying it; inflight_* dedupes a replay that
        # races the original serve thread (a second entry for the same
        # (rank, seq) would double-count ``fetched`` and strand the barrier).
        self.last_resp: Dict[int, Tuple[int, bytes]] = {}
        self.inflight_seq: Dict[int, int] = {}
        self.last_data_resp: Dict[int, Tuple[Tuple[int, int], bytes]] = {}
        self.inflight_data: Dict[int, Tuple[int, int]] = {}
        # ---- liveness: last frame time per rank, ranks inside the
        # reconnect-grace window, and how many heartbeat intervals each rank
        # has already been charged as missed
        self.last_seen: Dict[int, float] = {}
        self.disconnected: Dict[int, Tuple[float, str]] = {}
        self._hb_miss_counts: Dict[int, int] = {}
        # ranks currently observed silent, for flight-recorder flap events
        # only (the metric ledger above keeps its own accounting)
        self._hb_silent: set = set()
        # monotonic time of the last completed negotiation (/healthz
        # freshness age) — monotonic like every other liveness clock here,
        # so an NTP step/slew cannot misreport the stall age
        self.last_negotiation = 0.0
        self.warned: set = set()
        # ---- elastic membership (docs/elastic.md). Non-elastic jobs keep
        # members == range(world) for life, so every len(self.members)
        # below degenerates to self.world.
        self.elastic = elastic
        self.epoch = 0
        self.members: set = set(range(world))
        self.pending_joins: set = set()
        self.committed: set = set()
        self.reset_reason = ""
        # ---- storm-proof rendezvous (docs/control-plane.md): with
        # HOROVOD_ADMISSION_BATCH_MS set, joiner admission lingers until no
        # new joiner has arrived for that long (N simultaneous joins -> ONE
        # epoch bump), and losses observed close together coalesce into one
        # reset the same way. 0 (the default) keeps the historical
        # one-event-one-epoch behavior exactly.
        self.admission_batch_s = _env_float(
            "HOROVOD_ADMISSION_BATCH_MS", 0.0) / 1000.0
        self._pending_join_last_t = 0.0
        self._pending_lost: List[Tuple[int, str]] = []
        self._lost_first_t = 0.0
        # ---- hierarchical control plane: control frames that reached this
        # state machine (one per exchange() call, one per BATCH regardless
        # of how many ranks it carries) — the O(hosts)-not-O(ranks) claim is
        # asserted against this counter
        self.frames_in = 0
        # ---- N-tier grouped deposits (MSG_TBATCH, docs/control-plane.md):
        # per-seq count of ranks vouched by run-length groups (the barrier
        # test becomes a count compare, not a per-rank set walk), per-seq
        # evicted cache ids reported inside groups, per-seq grouped rank
        # sets (materialized ONLY when a straggler policy needs per-rank
        # exclusion checks), and the per-subtree response shard: one cached
        # reply per (subtree, seq) instead of one per rank, so rank-0 replay
        # state is bounded by its direct children
        self.gcounts: Dict[int, int] = {}
        self.ginvalid: Dict[int, set] = {}
        self.glists: Dict[int, set] = {}
        self.shards: Dict[str, Dict[int, bytes]] = {}
        self._tier_inflight: Dict[Tuple[str, int], int] = {}
        # coverage already deposited per in-flight (subtree, seq): a
        # mid-tier partial flush can legitimately split one seq across
        # frames, so "replay" means no NOVEL ranks, not just a seen key
        self._tier_runs: Dict[Tuple[str, int], List[Tuple[int, int]]] = {}
        # subtree registry: "t{tier}.{index}" -> (tier, runs) from the
        # latest MSG_TBATCH/MSG_THB, plus last-seen time — liveness above
        # the host tier is vouched per subtree, not per rank
        self.subtrees: Dict[str, Tuple[int, List[Tuple[int, int]]]] = {}
        self.subtree_seen: Dict[str, float] = {}
        # ---- standby replication: monotonic journal seq + attached shipper
        # queues (one per standby; items are (subtree_filter, queue) and
        # queue items are (msg_type, payload) tuples — a sink with a
        # subtree filter receives only that subtree's churn + global records)
        self.jseq = 0
        self._journal_sinks: List = []
        # optional hook run at the top of every negotiation — the
        # coordinator server points it at the fault injector so
        # die@coordinator / slow@coordinator fire deterministically per
        # negotiation round
        self.on_negotiate = None
        # host-wire data plane: (epoch, dseq) -> in-flight aggregation
        self.data: Dict[Tuple[int, int], dict] = {}
        # per-seq participant count at negotiation time (membership may have
        # changed by the time stragglers fetch)
        self.expected: Dict[int, int] = {}
        # ---- straggler-adaptive execution (docs/fault-tolerance.md): the
        # deadline policy (None unless HOROVOD_STRAGGLER_DEADLINE is set AND
        # the job is elastic — the XLA data plane cannot drop a participant
        # mid-psum, only the host-wire elastic plane can), per-rank first
        # deposit time of each in-flight barrier round, and rank -> host so
        # escalation can blacklist the right machine
        self.straggler = (straggler_mod.StragglerPolicy.from_env()
                          if elastic else None)
        self._deposit_t: Dict[int, Dict[int, float]] = {}
        self.rank_hosts: Dict[int, str] = {0: socket.gethostname()}
        # escalations the serve thread should report to the elastic driver
        # (host, reason); drained outside the lock
        self._promote_queue: List[Tuple[str, str]] = []
        # ---- async sharded checkpointing (docs/checkpoint.md): per-step
        # accumulation of MSG_CKPT_DONE reports; a bundle finalizes only
        # when every CURRENT member's shard of the same step has landed.
        # step -> {"epoch": int, "shards": {index: {"nbytes", "crc"}}}
        self.ckpt_pending: Dict[int, dict] = {}
        self.ckpt_last_final = -1
        # set by the rank-0 CkptManager: fn(step, epoch, shards_dict)
        self.on_ckpt_finalize = None

    def fence(self, reason: str) -> None:
        """Park this coordinator: it lost (or could not renew) its
        leadership lease, so serving ANY response from here on could
        double-apply a step the promoted standby also applies. Every
        blocked barrier wait releases with :class:`CoordinatorFencedError`
        and every future exchange raises it immediately."""
        with self.cv:
            if self.fenced:
                return
            self.fenced = True
            self.fence_reason = reason
            self.cv.notify_all()
        logger.error("coordinator: FENCED — %s; parking the exchange "
                     "(workers will redial and follow the promoted "
                     "standby; this process serves no further steps)",
                     reason)

    def _fence_check_locked(self) -> None:
        if self.fenced:
            raise CoordinatorFencedError(
                "coordinator fenced: %s" % self.fence_reason)

    # ---- client entry: one call per rank per tick
    def exchange(self, rank: int, seq: int, payload: bytes) -> bytes:
        with self.cv:
            self.frames_in += 1
            self._flush_lost_locked()
            self._fence_check_locked()
            if self.bye:
                return self._shutdown_bytes()
            last = self.last_resp.get(rank)
            if last is not None and last[0] == seq:
                # replayed request after a reconnect: answer from the cached
                # response instead of double-applying the request list
                logger.warning("coordinator: replaying cached response for "
                               "rank %s seq %s", rank, seq)
                return last[1]
            if self.inflight_seq.get(rank) == seq:
                # a replay racing the original serve thread (still blocked
                # in the barrier): wait for its result rather than entering
                # the exchange twice
                data = self._await_replay_locked(rank, seq)
                if data is not None:
                    return data
                # original died resultless; process normally
            self.inflight_seq[rank] = seq
            try:
                data = self._exchange_locked(rank, seq, payload)
            finally:
                if self.inflight_seq.get(rank) == seq:
                    del self.inflight_seq[rank]
                self.cv.notify_all()
            self.last_resp[rank] = (seq, data)
            return data

    def exchange_batch(self, entries):
        """One batched frame from a per-host sub-coordinator
        (docs/control-plane.md): deposit every entry, then collect each
        rank's response. Returns (replies, deferred) where replies is
        [(rank, seq, response_bytes)] and deferred is [(rank, seq,
        payload)] for prospective joiners — their admission wait can span
        whole commit rounds of the members in THIS batch, so the server
        answers them from dedicated threads via the ordinary
        :meth:`exchange` path instead of stalling the batch on them."""
        replies: List[Tuple[int, int, bytes]] = []
        deferred: List[Tuple[int, int, bytes]] = []
        waits: List[Tuple[int, int, str, object, bytes]] = []
        with self.cv:
            self.frames_in += 1
            instruments.coord_batch_ranks().labels(tier="host").observe(
                len(entries))
            self._flush_lost_locked()
            self._fence_check_locked()
            for rank, seq, payload in entries:
                if self.bye:
                    replies.append((rank, seq, self._shutdown_bytes()))
                    continue
                last = self.last_resp.get(rank)
                if last is not None and last[0] == seq:
                    replies.append((rank, seq, last[1]))
                    continue
                if self.elastic and rank not in self.members:
                    deferred.append((rank, seq, payload))
                    continue
                if self.inflight_seq.get(rank) == seq:
                    waits.append((rank, seq, "replay", None, payload))
                    continue
                self.inflight_seq[rank] = seq
                kind, val = self._deposit_locked(rank, seq, payload)
                if kind == "done":
                    if self.inflight_seq.get(rank) == seq:
                        del self.inflight_seq[rank]
                    self.last_resp[rank] = (seq, val)
                    replies.append((rank, seq, val))
                    self.cv.notify_all()
                else:
                    waits.append((rank, seq, kind, val, payload))
            for rank, seq, kind, val, payload in waits:
                try:
                    if kind == "replay":
                        data = self._await_replay_locked(rank, seq)
                        if data is None:
                            # original serve thread died resultless:
                            # process this entry normally
                            self.inflight_seq[rank] = seq
                            k2, v2 = self._deposit_locked(rank, seq,
                                                          payload)
                            data = (v2 if k2 == "done" else
                                    self._await_locked(rank, seq, v2))
                    else:
                        data = self._await_locked(rank, seq, val)
                finally:
                    if self.inflight_seq.get(rank) == seq:
                        del self.inflight_seq[rank]
                    self.cv.notify_all()
                self.last_resp[rank] = (seq, data)
                replies.append((rank, seq, data))
        return replies, deferred

    def exchange_tier(self, tier: int, subtree: str, groups):
        """One GROUPED frame from a tier aggregator (docs/control-plane.md):
        ``groups`` is [(seq, payload, runs)] where every rank in ``runs``
        submitted exactly ``payload``. Work here is O(groups), not
        O(ranks): the payload decodes once per group, the barrier advances
        by a count, the negotiation table stores one representative meta
        per group, and the replay cache keeps ONE response per
        (subtree, seq) instead of one per rank. Returns (reply_groups,
        deferred) where reply_groups is [(seq, response_bytes, runs)] and
        deferred is [(rank, seq, payload)] for prospective joiners."""
        replies: List[Tuple[int, bytes, list]] = []
        deferred: List[Tuple[int, int, bytes]] = []
        waits: List[Tuple[int, list, int, str, object]] = []
        with self.cv:
            self.frames_in += 1
            instruments.coord_batch_ranks().labels(tier=str(tier)).observe(
                sum(wire.runs_count(g[2]) for g in groups))
            self._flush_lost_locked()
            self._fence_check_locked()
            shard = self.shards.setdefault(subtree, {})
            if groups:
                # register the subtree's coverage (groups of one seq are
                # disjoint; across seqs they repeat, so take the widest seq)
                per_seq: Dict[int, list] = {}
                for gseq, _, gruns in groups:
                    per_seq[gseq] = wire.merge_runs(
                        per_seq.get(gseq, []), gruns)
                self.subtrees[subtree] = (
                    tier, max(per_seq.values(), key=wire.runs_count))
                self.subtree_seen[subtree] = time.monotonic()
            fresh: set = set()
            for seq, payload, runs in groups:
                n = wire.runs_count(runs)
                if self.bye:
                    replies.append((seq, self._shutdown_bytes(), runs))
                    continue
                if self.elastic:
                    # per-rank gatekeeping only exists in elastic mode; the
                    # static fast path never materializes rank lists
                    ranks = []
                    for r in wire.runs_to_ranks(runs):
                        if r in self.members:
                            ranks.append(r)
                        else:
                            deferred.append((r, seq, payload))
                    if not ranks:
                        continue
                    runs = wire.ranks_to_runs(ranks)
                    n = len(ranks)
                cached = shard.get(seq)
                if cached is not None:
                    # re-shipped batch after an aggregator reconnect:
                    # answered from the subtree shard, O(1) per group
                    replies.append((seq, cached, runs))
                    continue
                key = (subtree, seq)
                dep_runs, dep_n = runs, n
                if key in self._tier_inflight and key not in fresh:
                    novel = wire.runs_subtract(
                        runs, self._tier_runs.get(key, []))
                    if not novel:
                        # a re-shipped group racing its original handler
                        # thread: wait for the shard entry it will write
                        waits.append((seq, runs, n, "replay", None))
                        continue
                    # a mid-tier partial flush split this seq's coverage
                    # across frames: deposit only the novel ranks, but the
                    # reply still covers everything this frame vouched for
                    dep_runs, dep_n = novel, wire.runs_count(novel)
                decoded = wire.decode_request_list(payload)
                flags, cids, reqs, score, epoch = decoded
                if self.elastic:
                    if epoch != self.epoch:
                        replies.append((seq, self._ranks_changed_bytes(),
                                        runs))
                        continue
                    if flags & wire.REQ_COMMIT:
                        self.committed.update(ranks)
                        self._maybe_admit_locked()
                        if self.epoch != epoch:
                            replies.append(
                                (seq, self._ranks_changed_bytes(), runs))
                            continue
                if score is not None and self.tuner is not None:
                    self.round_bytes += score[0] * dep_n
                    self.round_seconds = max(self.round_seconds, score[1])
                rep = dep_runs[0][0]
                for cid in cids:
                    cmetas = self.cache_meta.get(cid)
                    m = None if cmetas is None else (
                        cmetas.get(rep) or cmetas.get(-1))
                    if m is not None:
                        self.cache_hits += dep_n
                        instruments.response_cache_hits().inc(dep_n)
                        if m.name in self.cache_ids:
                            self.cache_ids.move_to_end(m.name)
                        self._add_group_locked(m, rep, dep_n, dep_runs)
                    else:
                        self.ginvalid.setdefault(seq, set()).add(cid)
                        self.cache_misses += dep_n
                        instruments.response_cache_misses().inc(dep_n)
                for m in reqs:
                    self.cache_misses += dep_n
                    instruments.response_cache_misses().inc(dep_n)
                    self._add_group_locked(m, rep, dep_n, dep_runs)
                self.gcounts[seq] = self.gcounts.get(seq, 0) + dep_n
                if self.straggler is not None:
                    self.glists.setdefault(seq, set()).update(
                        wire.runs_to_ranks(dep_runs))
                self._tier_inflight[key] = self._tier_inflight.get(key,
                                                                   0) + 1
                self._tier_runs[key] = wire.merge_runs(
                    self._tier_runs.get(key, []), dep_runs)
                fresh.add(key)
                self._maybe_negotiate_locked(seq)
                waits.append((seq, runs, dep_n, "wait", self.epoch))
            for seq, runs, n, kind, entry_epoch in waits:
                key = (subtree, seq)
                try:
                    if kind == "replay":
                        data = self._await_tier_replay_locked(shard, key,
                                                              seq)
                    else:
                        data = self._await_tier_locked(seq, n, entry_epoch)
                        shard[seq] = data
                        if len(shard) > 4:
                            shard.pop(min(shard))
                finally:
                    if kind == "wait":
                        cnt = self._tier_inflight.get(key, 0) - 1
                        if cnt > 0:
                            self._tier_inflight[key] = cnt
                        else:
                            self._tier_inflight.pop(key, None)
                            self._tier_runs.pop(key, None)
                    self.cv.notify_all()
                replies.append((seq, data, runs))
        return replies, deferred

    def _await_tier_replay_locked(self, shard, key, seq) -> bytes:
        """A re-shipped group racing its original handler thread (still
        blocked in the barrier): wait for the shard entry it will write."""
        while True:
            self._fence_check_locked()
            if self.bye:
                return self._shutdown_bytes()
            cached = shard.get(seq)
            if cached is not None:
                return cached
            if key not in self._tier_inflight:
                # original vanished resultless — only reachable through a
                # membership reset clearing the shard; answer accordingly
                return (self._ranks_changed_bytes() if self.elastic
                        else self._shutdown_bytes())
            self.cv.wait(timeout=0.5)

    def _await_tier_locked(self, seq: int, n: int,
                           entry_epoch: int) -> bytes:
        """Barrier wait for a grouped deposit covering ``n`` ranks: all of
        them fetch in one count bump."""
        while seq not in self.resps:
            self._fence_check_locked()
            if self.bye:
                return self._shutdown_bytes()
            if self.elastic and self.epoch != entry_epoch:
                return self._ranks_changed_bytes()
            self.cv.wait(timeout=0.5)
            self._flush_lost_locked()
        data = self.resps[seq]
        self.fetched[seq] = self.fetched.get(seq, 0) + n
        if self.fetched[seq] >= self.expected.get(seq, self.world):
            self._drop_barrier_locked(seq)
        return data

    def _add_group_locked(self, m, rep: int, n: int, runs) -> None:
        """Grouped deposit into the negotiation table: one representative
        meta plus a count, instead of n per-rank dict writes. Ragged
        collectives (ALLGATHER/ALLTOALL) still need per-rank metas for
        their size blocks, so those expand — identical payloads mean
        identical metas, so expansion is a pure fan-out."""
        p = self.table.get(m.name)
        if p is None:
            p = _Pending(self.order_ctr)
            self.order_ctr += 1
            self.table[m.name] = p
        p.metas[rep] = m
        # the shared -1 slot flows into cache_meta on assignment, so later
        # grouped cache hits resolve even when the group's lowest rank (the
        # representative) shifts across rounds
        p.metas[-1] = m
        p.grouped.add(rep)
        p.grouped.add(-1)
        p.arrivals.setdefault(rep, time.monotonic())
        p.gcount += n
        if int(m.rtype) in (int(RequestType.ALLGATHER),
                            int(RequestType.ALLTOALL)):
            for r in wire.runs_to_ranks(runs):
                p.metas[r] = m
                p.grouped.add(r)

    def mark_subtree_alive(self, subtree: str, tier: int, runs) -> None:
        """MSG_THB bookkeeping: the subtree's aggregator vouches for every
        rank in ``runs``. Tier-vouched ranks are NOT tracked in the
        per-rank ``last_seen`` ledger (that would be O(ranks) per beat);
        a vouched rank inside the reconnect grace window is released."""
        with self.cv:
            self.subtrees[subtree] = (tier, runs)
            self.subtree_seen[subtree] = time.monotonic()
            if self.disconnected:
                for r in list(self.disconnected):
                    if wire.runs_contain(runs, r):
                        self.disconnected.pop(r, None)
                        self._hb_miss_counts.pop(r, None)

    def subtree_disconnected(self, subtree: str, reason: str) -> None:
        """The subtree's upstream connection died: open the ordinary
        reconnect grace window for every rank it vouched for (one log line,
        not O(ranks) of them — the aggregator usually re-homes to a tier
        standby and the next vouch clears all of this)."""
        with self.cv:
            info = self.subtrees.get(subtree)
            if info is None or self.bye:
                return
            tier, runs = info
            now = time.monotonic()
            opened = 0
            for r in wire.runs_to_ranks(runs):
                if r in self.members and r not in self.disconnected:
                    self.disconnected[r] = (
                        now, "tier subtree %s lost: %s" % (subtree, reason))
                    opened += 1
        if opened:
            logger.warning(
                "coordinator: tier-%d subtree %s connection lost (%s); "
                "reconnect grace window open for %d ranks", tier, subtree,
                reason, opened)

    def _covering_subtree_locked(self, ranks) -> Tuple[str, int]:
        """The registered subtree containing ALL of ``ranks`` (top-tier
        subtrees are disjoint), or ("", 0) for cross-subtree/global churn —
        the journal shard tag for this membership change."""
        for name, (tier, runs) in self.subtrees.items():
            if all(wire.runs_contain(runs, r) for r in ranks):
                return name, tier
        return "", 0

    def _await_replay_locked(self, rank: int, seq: int) -> Optional[bytes]:
        """Wait out a replay racing the original serve thread. Returns the
        cached response, shutdown bytes, or None if the original vanished
        without producing a result (caller re-enters normally)."""
        while True:
            self._fence_check_locked()
            if self.bye:
                return self._shutdown_bytes()
            last = self.last_resp.get(rank)
            if last is not None and last[0] == seq:
                return last[1]
            if self.inflight_seq.get(rank) != seq:
                return None
            self.cv.wait(timeout=0.5)

    def _exchange_locked(self, rank: int, seq: int, payload: bytes) -> bytes:
        # runs under self.cv (the exchange() wrapper holds it)
        kind, val = self._deposit_locked(rank, seq, payload)
        if kind == "done":
            return val
        if kind == "join":
            return self._await_join_locked(rank)
        return self._await_locked(rank, seq, val)

    def _deposit_locked(self, rank: int, seq: int, payload: bytes):
        """Phase 1 of an exchange: decode + elastic gatekeeping + deposit
        into the seq barrier (negotiating if this deposit completes it).
        Returns ("done", response_bytes) for immediately-answerable frames,
        ("join", None) for a prospective joiner (caller must run the
        admission wait) or ("wait", entry_epoch) after a deposit."""
        flags_cached_reqs_score = wire.decode_request_list(payload)
        score = flags_cached_reqs_score[3]
        if self.elastic:
            if rank not in self.members:
                # prospective joiner: blocks until every current member
                # reaches a commit boundary, then enters under the bumped
                # epoch (re-rendezvous; docs/elastic.md)
                self.pending_joins.add(rank)
                self._pending_join_last_t = time.monotonic()
                self._maybe_admit_locked()
                return ("join", None)
            if flags_cached_reqs_score[4] != self.epoch:
                # stale-epoch submission (queued before a reset): fail
                # fast instead of entering a barrier the current member
                # set can never complete
                return ("done", self._ranks_changed_bytes())
            if flags_cached_reqs_score[0] & wire.REQ_COMMIT:
                self.committed.add(rank)
                self._maybe_admit_locked()
                if self.epoch != flags_cached_reqs_score[4]:
                    # this commit admitted joiners; the frame itself is
                    # now stale — sender re-syncs like everyone else
                    return ("done", self._ranks_changed_bytes())
        if score is not None and self.tuner is not None:
            self.round_bytes += score[0]
            self.round_seconds = max(self.round_seconds, score[1])
        self.lists.setdefault(seq, {})[rank] = flags_cached_reqs_score[:3]
        if self.straggler is not None:
            self._observe_arrival_locked(rank, seq)
        self._maybe_negotiate_locked(seq)
        return ("wait", self.epoch)

    def _observe_arrival_locked(self, rank: int, seq: int) -> None:
        """Straggler policy bookkeeping: record this rank's first deposit
        time for the round, and once EVERY member has deposited (excluded
        ranks trail in late — that lateness is exactly the measurement)
        feed the completed arrival row to the policy and act on its
        exclusion/readmission transitions."""
        pol = self.straggler
        pol.note_deposit(rank, seq)
        row = self._deposit_t.setdefault(seq, {})
        row.setdefault(rank, time.monotonic())
        if len(row) < len(self.members):
            return
        from ..goodput import ledger as _goodput

        led = _goodput.active()
        events = pol.observe_round(self._deposit_t.pop(seq))
        for r in events["excluded"]:
            host = self.rank_hosts.get(r, "?")
            logger.warning(
                "straggler policy: excluding rank %d (host %s) after %d "
                "late rounds; collectives proceed over %d survivors",
                r, host, pol.patience, len(self.members) - len(pol.excluded))
            _blackbox.record(_blackbox.K_EXCLUDED, "rank_%d" % r,
                             "excluded host=%s episode=%d"
                             % (host, pol.episodes.get(r, 0)))
            if led is not None:
                led.note_excluded(r, True)
        for r in events["readmitted"]:
            logger.info("straggler policy: re-admitting rank %d (host %s)",
                        r, self.rank_hosts.get(r, "?"))
            _blackbox.record(_blackbox.K_EXCLUDED, "rank_%d" % r,
                             "readmitted host=%s"
                             % self.rank_hosts.get(r, "?"))
            if led is not None:
                led.note_excluded(r, False)
        if events["excluded"] or events["readmitted"]:
            instruments.excluded_rank().set(
                max(pol.excluded) if pol.excluded else -1)
            # the quorum just changed: barriers blocked on the old set may
            # be complete under the new one
            for s in sorted(self.lists):
                self._maybe_negotiate_locked(s)

    def _maybe_negotiate_locked(self, seq: int) -> None:
        # a coalescing loss reset is pending: completing the barrier now
        # would negotiate against a member set about to shrink — hold until
        # the reset flushes (bounded by admission_batch_s)
        if (seq not in self.lists and seq not in self.gcounts) \
                or self._pending_lost:
            return
        row = self.lists.get(seq, {})
        if self.straggler is not None and self.straggler.excluded:
            # partial barrier: complete once every NON-excluded member has
            # deposited; the excluded rank trails and fetches late
            gset = self.glists.get(seq, ())
            ready = all(m in row or m in gset for m in self.members
                        if m not in self.straggler.excluded)
        else:
            # grouped tier deposits are counted, not enumerated: the
            # barrier is complete when flat deposits + vouched group ranks
            # cover the member set (flat mode keeps the exact old compare)
            ready = (len(row) + self.gcounts.get(seq, 0)
                     == len(self.members))
        if ready:
            # expected counts ALL members: the excluded rank still fetches
            # this seq's response (after the fact), so the cached response
            # must survive until it does
            self.expected[seq] = len(self.members)
            self.resps[seq] = self._negotiate(self.lists.pop(seq, {}), seq)
            self.cv.notify_all()

    def _await_join_locked(self, rank: int) -> bytes:
        while rank not in self.members:
            self._fence_check_locked()
            if self.bye:
                self.pending_joins.discard(rank)
                return self._shutdown_bytes()
            # re-check on every wake: with admission batching the linger
            # window expires on the clock, not on a member event
            self._maybe_admit_locked()
            if rank in self.members:
                break
            self.cv.wait(timeout=0.1 if self.admission_batch_s else 0.5)
        return self._ranks_changed_bytes()

    def _await_locked(self, rank: int, seq: int, entry_epoch: int) -> bytes:
        while seq not in self.resps:
            self._fence_check_locked()
            if self.bye:
                return self._shutdown_bytes()
            if self.elastic and self.epoch != entry_epoch:
                # membership reset while blocked: withdraw our entry and
                # realign instead of waiting on a dead barrier
                if seq in self.lists:
                    self.lists[seq].pop(rank, None)
                return self._ranks_changed_bytes()
            self.cv.wait(timeout=0.5)
            self._flush_lost_locked()
        data = self.resps[seq]
        self.fetched[seq] = self.fetched.get(seq, 0) + 1
        if self.fetched[seq] >= self.expected.get(seq, self.world):
            self._drop_barrier_locked(seq)
        return data

    def _drop_barrier_locked(self, seq: int) -> None:
        """Everyone expected has fetched: release every remnant of the seq
        barrier (a trailing excluded rank's late deposit can recreate the
        ``lists`` entry AFTER partial negotiation popped it)."""
        self.resps.pop(seq, None)
        self.fetched.pop(seq, None)
        self.expected.pop(seq, None)
        self.lists.pop(seq, None)
        self.gcounts.pop(seq, None)
        self.glists.pop(seq, None)

    # ---- elastic membership (all under self.cv unless noted)
    def rank_lost(self, rank: int, reason: str) -> None:
        """A member dropped its control-plane connection: remove it, bump the
        epoch and release every blocked barrier with RESP_RANKS_CHANGED so
        survivors re-sync instead of dying (the elastic alternative to
        :meth:`set_bye`)."""
        with self.cv:
            if self.bye or rank not in self.members:
                return
            self.members.discard(rank)
            for per_rank in (self.disconnected, self.last_seen,
                             self._hb_miss_counts, self.last_resp,
                             self.inflight_seq, self.last_data_resp,
                             self.inflight_data):
                per_rank.pop(rank, None)
            self._hb_silent.discard(rank)
            if self.straggler is not None:
                self.straggler.forget(rank)
            instruments.elastic_rank_lost().inc()
            # flight recorder: remember the death so rank 0's bundle carries
            # a stub for the rank that will never ship its own dump; a stale
            # metrics report from it must also never resurrect its gauges
            _blackbox.note_dead_rank(rank, reason)
            from ..metrics import drop_report
            drop_report(rank)
            if self.admission_batch_s > 0:
                # storm-proofing: losses observed close together coalesce
                # into ONE epoch bump; the reset flushes once no new loss
                # has widened the window past admission_batch_s
                if not self._pending_lost:
                    self._lost_first_t = time.monotonic()
                self._pending_lost.append((rank, reason))
                self.cv.notify_all()
                return
            self._reset_locked(
                f"worker lost: rank {rank} dropped its control-plane "
                f"connection ({reason})", ranks=(rank,))

    def _flush_lost_locked(self, force: bool = False) -> None:
        """Apply a coalesced loss reset once the batching window closes
        (called from exchange entry, barrier wait wakes, and the liveness
        monitor — whichever observes expiry first)."""
        if not self._pending_lost:
            return
        if (not force and time.monotonic() - self._lost_first_t
                < self.admission_batch_s):
            return
        lost, self._pending_lost = self._pending_lost, []
        ranks = [r for r, _ in lost]
        if len(ranks) == 1:
            self._reset_locked(
                f"worker lost: rank {ranks[0]} dropped its control-plane "
                f"connection ({lost[0][1]})", ranks=ranks)
        else:
            reasons = "; ".join(f"rank {r}: {why}" for r, why in lost)
            self._reset_locked(
                f"workers lost: ranks {ranks} dropped their control-plane "
                f"connections in one {self.admission_batch_s * 1000:g}ms "
                f"window ({reasons})", ranks=ranks)

    # ---- liveness (docs/fault-tolerance.md)
    def mark_alive(self, rank: int) -> None:
        """Any frame from a rank proves it alive (heartbeats exist so idle
        or long-compiling workers keep producing frames)."""
        with self.cv:
            self.last_seen[rank] = time.monotonic()

    def marks_alive(self, ranks) -> None:
        """Batched liveness proof (hierarchical mode): every listed rank is
        alive per its sub-coordinator, which also cancels any reconnect
        grace clock — a rank whose frames ride a host batch never sends a
        per-rank MSG_RESUME of its own."""
        now = time.monotonic()
        with self.cv:
            for r in ranks:
                self.last_seen[r] = now
                self.disconnected.pop(r, None)
                self._hb_miss_counts.pop(r, None)

    def rank_disconnected(self, rank: int, reason: str) -> None:
        """A serve thread lost its connection. Not yet fatal: start the
        reconnect-grace clock; :meth:`check_liveness` declares the rank lost
        only if no resume arrives within HOROVOD_RECONNECT_GRACE."""
        with self.cv:
            if self.bye or rank not in self.members:
                return
            if rank in self.disconnected:
                return
            self.disconnected[rank] = (time.monotonic(), reason)
            logger.warning(
                "coordinator: rank %s disconnected (%s); waiting for a "
                "resume within the reconnect grace window", rank, reason)

    def rank_reconnected(self, rank: int, last_acked: int) -> None:
        """MSG_RESUME arrived: cancel the grace clock and reset the
        heartbeat ledger. The replayed request (if any) follows on the new
        connection and is answered via the replay cache."""
        with self.cv:
            self.disconnected.pop(rank, None)
            self._hb_miss_counts.pop(rank, None)
            if rank in self._hb_silent:
                self._hb_silent.discard(rank)
                _blackbox.record(_blackbox.K_HEARTBEAT, "rank_%d" % rank,
                                 "rank %d ok (resumed)" % rank, rank=rank)
            self.last_seen[rank] = time.monotonic()
            _blackbox.record(_blackbox.K_RECONNECT, "rank_%d" % rank,
                             "resumed (last acked seq %s)" % last_acked,
                             rank=rank)
            logger.warning("coordinator: rank %s resumed its control-plane "
                           "connection (last acked seq %s)", rank, last_acked)

    def check_liveness(self, grace_s: float, hb_interval: float,
                       hb_timeout: float) -> None:
        """Periodic sweep (CoordinatorServer monitor thread): charge missed
        heartbeat intervals and declare ranks dead — disconnected past the
        grace window, or silent past HOROVOD_HEARTBEAT_TIMEOUT (the
        silently-dead case where TCP never errors). Dead ranks feed the
        elastic ``rank_lost`` path; non-elastic jobs shut down coordinated,
        exactly as an observed connection loss used to."""
        now = time.monotonic()
        lost: List[Tuple[int, str]] = []
        with self.cv:
            if self.bye:
                return
            self._flush_lost_locked()
            for rank, (t0, reason) in list(self.disconnected.items()):
                if now - t0 > grace_s:
                    lost.append((rank, f"no reconnect within the "
                                 f"{grace_s:g}s grace window after: "
                                 f"{reason}"))
            if hb_interval > 0:
                for rank, seen in list(self.last_seen.items()):
                    # a rank with an exchange in flight is provably alive:
                    # its serve thread is parked in the barrier and cannot
                    # drain heartbeats queued behind the request frame
                    if (rank == 0 or rank not in self.members
                            or rank in self.disconnected
                            or rank in self.inflight_seq
                            or rank in self.inflight_data):
                        continue
                    age = now - seen
                    misses = int(age // hb_interval)
                    prev = self._hb_miss_counts.get(rank, 0)
                    if misses > prev:
                        instruments.heartbeat_misses().inc(misses - prev)
                        self._hb_miss_counts[rank] = misses
                    # flight-recorder flap edges, tracked apart from the
                    # metric ledger (whose high-water counts never reset on
                    # silent recovery): one miss event per silent episode,
                    # one ok event when frames resume
                    if misses >= 1 and rank not in self._hb_silent:
                        self._hb_silent.add(rank)
                        _blackbox.record(
                            _blackbox.K_HEARTBEAT, "rank_%d" % rank,
                            "rank %d missed %d heartbeat interval(s)"
                            % (rank, misses), rank=rank)
                    elif misses == 0 and rank in self._hb_silent:
                        self._hb_silent.discard(rank)
                        _blackbox.record(
                            _blackbox.K_HEARTBEAT, "rank_%d" % rank,
                            "rank %d ok (heartbeats resumed)" % rank,
                            rank=rank)
                    if hb_timeout > 0 and age > hb_timeout:
                        lost.append((rank, f"no heartbeat for {age:.1f}s "
                                     "(HOROVOD_HEARTBEAT_TIMEOUT="
                                     f"{hb_timeout:g})"))
        for rank, why in lost:
            if self.elastic and rank > 0:
                self.rank_lost(rank, why)
            else:
                # non-elastic: the job dies with the rank, but the bundle
                # still wants a stub naming who was declared dead and why
                _blackbox.note_dead_rank(rank, why)
                self.set_bye(f"worker rank {rank} declared dead: {why}")

    def _maybe_admit_locked(self) -> None:
        if not self.pending_joins:
            if self.committed >= self.members:
                self.committed.clear()  # boundary passed with no joiners
            return
        if self.committed >= self.members:
            if (self.admission_batch_s > 0
                    and time.monotonic() - self._pending_join_last_t
                    < self.admission_batch_s):
                # admission linger (HOROVOD_ADMISSION_BATCH_MS): a join
                # storm lands as ONE epoch bump — hold the boundary open
                # until no new joiner has arrived for the whole window
                return
            admitted = sorted(self.pending_joins)
            self.members |= self.pending_joins
            self.pending_joins.clear()
            if len(admitted) > 1:
                instruments.epoch_coalesced_joins().inc(len(admitted) - 1)
            from ..metrics import readmit_report
            for r in admitted:
                readmit_report(r)
            self._reset_locked(
                f"worker joined: rank(s) {admitted} admitted at commit "
                "boundary", ranks=admitted)

    # ---- async sharded checkpointing: consistency stamps (fire-and-forget
    # frames, same interleaving contract as MSG_METRICS)
    def ckpt_mark(self, rank: int, step: int, epoch: int) -> None:
        """A member snapshotted its shard for ``step``: open (or refresh)
        the step's accumulation and surface bundle age. Stamps from a
        stale epoch are dropped — the sender will re-mark after resync."""
        with self.cv:
            if epoch != self.epoch or rank not in self.members:
                return
            self.ckpt_pending.setdefault(
                step, {"epoch": self.epoch, "shards": {}})
            age = (step - self.ckpt_last_final
                   if self.ckpt_last_final >= 0 else 0)
        instruments.ckpt_bundle_age_steps().set(max(0, age))

    def ckpt_done(self, rank: int, step: int, epoch: int, index: int,
                  nbytes: int, crc: int) -> None:
        """A member's shard file landed. When every CURRENT member's shard
        of the same step is in, the bundle finalizes (manifest rename via
        ``on_ckpt_finalize``) — the only point a bundle becomes
        restorable."""
        fire = None
        with self.cv:
            if epoch != self.epoch or rank not in self.members:
                return
            ent = self.ckpt_pending.setdefault(
                step, {"epoch": self.epoch, "shards": {}})
            ent["shards"][index] = {"nbytes": int(nbytes), "crc": int(crc)}
            if (len(ent["shards"]) >= len(self.members)
                    and step > self.ckpt_last_final):
                self.ckpt_last_final = step
                # older partial steps can never finalize out of order
                self.ckpt_pending = {s: e for s, e in
                                     self.ckpt_pending.items() if s > step}
                fire = (step, ent["epoch"], dict(ent["shards"]))
        if fire is not None:
            instruments.ckpt_bundle_age_steps().set(0)
            cb = self.on_ckpt_finalize
            if cb is not None:
                try:
                    cb(*fire)
                except Exception:
                    logger.warning("ckpt: bundle finalize for step %d "
                                   "failed", fire[0], exc_info=True)

    def _reset_locked(self, reason: str, ranks=()) -> None:
        """Bump the membership epoch and drop every piece of state tied to
        the old rank set: pending barriers, negotiated-but-unfetched
        responses, the negotiation table, the response cache (ids were
        assigned against the old member set) and in-flight data
        aggregations. Blocked waiters observe the epoch change and return
        RESP_RANKS_CHANGED / DATA_RANKS_CHANGED to their controllers.
        ``ranks`` (the ranks whose churn caused this reset) shards the
        journal record: a change contained in one registered subtree
        replicates to that subtree's standby, not to every tier."""
        self.epoch += 1
        instruments.elastic_epoch().set(self.epoch)
        self.reset_reason = reason
        self.committed.clear()
        self.table.clear()
        self.order_ctr = 0
        self.warned.clear()
        self.joined &= self.members
        self.last_joined = -1
        self.cache_ids.clear()
        self.cache_meta.clear()  # next_cache_id stays monotonic: old ids
        # must never alias tensors cached under the new epoch
        self.lists.clear()
        self.resps.clear()
        self.fetched.clear()
        self.expected.clear()
        self.data.clear()
        # tier-grouped barrier state is epoch-scoped too: blocked
        # exchange_tier handlers observe the epoch bump, and replay waiters
        # see their inflight key vanish
        self.gcounts.clear()
        self.ginvalid.clear()
        self.glists.clear()
        self.shards.clear()
        self._tier_inflight.clear()
        self._tier_runs.clear()
        # replay caches die with the epoch (seqs realign to epoch *
        # EPOCH_SEQ_BASE, so no stale entry could match anyway)
        self.last_resp.clear()
        self.last_data_resp.clear()
        # checkpoint stamps are epoch-scoped: a bundle mid-flight under the
        # old member set can never complete (the completeness test is "every
        # CURRENT member reported"), so pending accumulations are dropped
        # and the previous complete bundle stays authoritative
        self.ckpt_pending.clear()
        # straggler counters are meaningless across a membership change
        # (seqs realign, the member set shifts); episode history survives
        # inside the policy for the chronic_straggler doctor signature
        self._deposit_t.clear()
        if self.straggler is not None:
            self.straggler.reset()
            instruments.excluded_rank().set(-1)
        _blackbox.record(_blackbox.K_EPOCH, "epoch_%d" % self.epoch,
                         "%s; members now %s" % (reason,
                                                 sorted(self.members)))
        logger.warning("elastic: membership epoch %d (%s); members now %s",
                       self.epoch, reason, sorted(self.members))
        # standby replication: every epoch change is one journal record
        # (membership is the ONLY durable state — see MSG_REPL_HELLO)
        self.jseq += 1
        if self._journal_sinks:
            subtree, _ = (self._covering_subtree_locked(ranks)
                          if ranks else ("", 0))
            rec = wire.encode_coord_journal(self.jseq, self.epoch,
                                            sorted(self.members), reason,
                                            subtree)
            for q, sfilter in self._journal_sinks:
                # a subtree-scoped sink only carries its own churn; the
                # root sink (filter "") carries everything, and global
                # churn (tag "") fans out to every sink
                if sfilter and subtree and sfilter != subtree:
                    continue
                q.put((MSG_JOURNAL, rec))
            instruments.standby_journal_lag().labels(tier="root").set(
                max(q.qsize() for q, _ in self._journal_sinks))
        self._publish_members_locked()
        self.cv.notify_all()

    def attach_journal(self, q, subtree: str = "") -> None:
        """Attach a standby's shipper queue: enqueue one snapshot of the
        current membership state, then a journal record per epoch change
        until :meth:`detach_journal` (docs/control-plane.md). A non-empty
        ``subtree`` scopes the stream: only records tagged with that
        subtree (or global, untagged churn) are shipped."""
        with self.cv:
            snap = wire.encode_coord_snapshot(
                self.jseq, self.epoch, self.world, self.elastic,
                sorted(self.members), self.next_cache_id)
            q.put((MSG_SNAPSHOT, snap))
            self._journal_sinks.append((q, subtree))

    def detach_journal(self, q) -> None:
        with self.cv:
            self._journal_sinks = [(sq, sf) for sq, sf in
                                   self._journal_sinks if sq is not q]

    def _publish_members_locked(self) -> None:
        """Best-effort membership advertisement through the launcher KV store
        (key ``elastic/members`` = "epoch;r0,r1,..."), off-thread so a slow
        KV server never stalls the coordinator lock."""
        kv_addr = os.environ.get("HVD_KV_ADDR")
        if not kv_addr:
            return
        payload = (f"{self.epoch};"
                   f"{','.join(str(r) for r in sorted(self.members))}")

        def _put():
            try:
                from ..run.rendezvous import KVStoreClient

                KVStoreClient(kv_addr, os.environ.get("HVD_SECRET", "")).put(
                    "elastic", "members", payload.encode())
            except Exception:
                logger.debug("elastic: membership publish failed",
                             exc_info=True)

        threading.Thread(target=_put, name="hvd_elastic_members",
                         daemon=True).start()

    def note_rank_host(self, rank: int, host: str) -> None:
        """Remember which machine a rank connected from (HELLO/RESUME peer
        address) so straggler escalation can blacklist the HOST, not just
        the rank."""
        if host:
            with self.cv:
                self.rank_hosts[rank] = host

    def _notify_driver_failure(self, host: str, reason: str) -> None:
        """Report a chronically slow host to the elastic driver (when one
        launched us) so the blacklist keeps rescheduling off it and a hot
        spare is promoted. Off-thread: this runs from inside a negotiation
        under self.cv and must never block the control plane on RPC."""
        driver_addr = os.environ.get("HVD_DRIVER_ADDR")
        if not driver_addr or not host or host == "?":
            return

        def _report():
            try:
                from ..run.service import DriverClient

                ip, port = driver_addr.rsplit(":", 1)
                DriverClient((ip, int(port)),
                             os.environ.get("HVD_SECRET", "")
                             ).notify_host_failure(host, reason)
            except Exception:
                logger.debug("straggler: driver failure report failed",
                             exc_info=True)

        threading.Thread(target=_report, name="hvd_straggler_promote",
                         daemon=True).start()

    def _ranks_changed_bytes(self) -> bytes:
        return wire.encode_response_list(
            wire.RESP_RANKS_CHANGED, -1, [], [], [], self.reset_reason,
            epoch=self.epoch, members=sorted(self.members))

    # ---- elastic host-wire data plane
    def data_exchange(self, rank: int, payload: bytes) -> bytes:
        """Aggregate one rank's allreduce/broadcast payload for (epoch, dseq)
        over the current member set; blocks until all members contribute.
        The reply carries the participant count so Average divides by the
        epoch's actual world size. Replays after a reconnect are answered
        from the per-rank response cache, mirroring :meth:`exchange`."""
        (epoch, dseq, op, root, dtype, shape,
         raw) = wire.decode_data_request(payload)
        key = (epoch, dseq)
        with self.cv:
            self._fence_check_locked()
            if self.bye:
                return self._data_error_locked()
            last = self.last_data_resp.get(rank)
            if last is not None and last[0] == key:
                logger.warning("coordinator: replaying cached data response "
                               "for rank %s (epoch %s, dseq %s)",
                               rank, epoch, dseq)
                return last[1]
            if self.inflight_data.get(rank) == key:
                while True:
                    self._fence_check_locked()
                    if self.bye:
                        return self._data_error_locked()
                    last = self.last_data_resp.get(rank)
                    if last is not None and last[0] == key:
                        return last[1]
                    if self.inflight_data.get(rank) != key:
                        break
                    self.cv.wait(timeout=0.5)
            self.inflight_data[rank] = key
            try:
                data = self._data_exchange_locked(rank, key, op, root,
                                                  dtype, shape, raw)
            finally:
                if self.inflight_data.get(rank) == key:
                    del self.inflight_data[rank]
                self.cv.notify_all()
            self.last_data_resp[rank] = (key, data)
            return data

    def _data_exchange_locked(self, rank: int, key: Tuple[int, int],
                              op: int, root: int, dtype: str, shape,
                              raw: bytes) -> bytes:
        # runs under self.cv (the data_exchange() wrapper holds it)
        epoch, dseq = key
        if (not self.elastic or rank not in self.members
                or epoch != self.epoch):
            return self._ranks_changed_data_locked()
        agg = self.data.get(key)
        if agg is None:
            # expected: who must contribute before combining (shrinks live
            # with straggler exclusion); fetchers: who will FETCH the result
            # (always every member — a trailing excluded rank still fetches,
            # late, so the agg must survive until it does)
            agg = self.data[key] = {"parts": {}, "result": None,
                                    "nparticipants": 0, "fetched": 0,
                                    "expected": set(self.members),
                                    "fetchers": set(self.members),
                                    "contributors": None}
        agg["parts"][rank] = (op, root, dtype, shape, raw)
        self._maybe_combine_locked(agg)
        while agg["result"] is None:
            self._fence_check_locked()
            if self.bye:
                return self._data_error_locked()
            if self.epoch != epoch:
                return self._ranks_changed_data_locked()
            # exclusion can flip while we wait (the policy acts on control
            # frames): re-check whether the surviving subgroup is complete
            self._maybe_combine_locked(agg)
            if agg["result"] is not None:
                break
            self.cv.wait(timeout=0.5)
        partial = set(agg["contributors"] or ()) != agg["fetchers"]
        out = wire.encode_data_result(wire.DATA_OK, epoch,
                                      agg["nparticipants"],
                                      agg["contributors"] if partial
                                      else None,
                                      agg["result"])
        agg["fetched"] += 1
        if agg["fetched"] >= len(agg["fetchers"]):
            self.data.pop(key, None)
        return out

    def _maybe_combine_locked(self, agg: dict) -> None:
        """Combine once every non-excluded expected rank has contributed.
        The contributor list is snapshotted at combine time: a late part
        that beats the combine IS included (and its sender learns it was,
        via the members field of the reply, so its EF residual clears)."""
        if agg["result"] is not None:
            return
        need = set(agg["expected"])
        if self.straggler is not None and self.straggler.excluded:
            survivors = need - self.straggler.excluded
            if survivors:
                need = survivors
        if set(agg["parts"]) >= need:
            op, root = next(iter(agg["parts"].values()))[:2]
            if (op == int(RequestType.BROADCAST)
                    and root not in agg["parts"]):
                # a broadcast has exactly one source of truth: even an
                # excluded root must land its part before we combine
                return
            agg["contributors"] = sorted(agg["parts"])
            agg["result"] = self._combine(agg)
            agg["nparticipants"] = len(agg["parts"])
            if set(agg["contributors"]) != agg["fetchers"]:
                instruments.partial_collectives().inc()
            self.cv.notify_all()

    @staticmethod
    def _combine(agg: dict) -> bytes:
        import numpy as np

        parts = agg["parts"]
        op, root, dtype, shape, _ = parts[min(parts)]
        if op == int(RequestType.BROADCAST):
            # epoch checks guarantee the root is a live member with a part
            return parts[root][4]
        acc = None
        for r in sorted(parts):
            arr = np.frombuffer(parts[r][4], dtype=np.dtype(dtype))
            acc = arr.copy() if acc is None else acc + arr
        return acc.astype(np.dtype(dtype), copy=False).tobytes()

    def _ranks_changed_data_locked(self) -> bytes:
        return wire.encode_data_result(
            wire.DATA_RANKS_CHANGED, self.epoch, 0, sorted(self.members),
            self.reset_reason.encode())

    def _data_error_locked(self) -> bytes:
        msg = self.shutdown_reason or "control plane shut down"
        return wire.encode_data_result(wire.DATA_ERROR, self.epoch, 0, None,
                                       msg.encode())

    def set_bye(self, reason: str = "") -> None:
        """A rank left (clean BYE or dead connection): coordinated shutdown.

        Parity: the reference sets ``shut_down`` in the response list so every
        rank's background loop exits together (`operations.cc:511-517`); the
        launcher-level first-failure kill covers the crash case — here the
        control plane itself observes the death."""
        with self.cv:
            self.bye = True
            if reason and not self.shutdown_reason:
                self.shutdown_reason = reason
                _blackbox.record(_blackbox.K_ERROR, "shutdown", reason)
            for seq in list(self.lists):
                self.resps[seq] = self._shutdown_bytes()
                del self.lists[seq]
            self.cv.notify_all()

    def _shutdown_bytes(self) -> bytes:
        return wire.encode_response_list(wire.RESP_SHUTDOWN, -1, [], [], [],
                                         self.shutdown_reason)

    # ---- negotiation (single-threaded under self.cv)
    def _meta_of(self, rank: int, cid: int) -> Optional[ReqMeta]:
        metas = self.cache_meta.get(cid)
        return None if metas is None else metas.get(rank)

    def _tune(self) -> Optional[Tuple]:
        """Feed the round's aggregated score to the GP/EI and return the
        tuned fields to broadcast — (threshold, cycle_ms) always, plus the
        bitwidth cap when the adaptive wire is in play; must run under
        self.cv."""
        if self.tuner is None:
            return None
        rb, rs = self.round_bytes, self.round_seconds
        if rb > 0 and rs > 0:
            changed = self.tuner.update(rb, rs)
            if changed:
                self.threshold = int(self.tuner.fusion_threshold())
            if self.bw_tuner is not None:
                # the same wire-true score drives the bitwidth-cap search:
                # round_bytes already reflects whatever grids the current
                # cap allowed, so each episode scores its cap directly
                self.bw_tuner.observe(rb, rs)
            if changed or self.tuner.active():
                # stop logging once the GP settles (bounded file growth;
                # the settling update itself is the last line)
                from ..utils.autotune_log import log_sample

                log_sample(os.environ.get("HOROVOD_AUTOTUNE_LOG"),
                           rb, rs,
                           self.threshold, float(self.tuner.cycle_time_ms()))
            self.round_bytes = 0
            self.round_seconds = 0.0
        self.tuned = (self.threshold, float(self.tuner.cycle_time_ms()))
        if self.bw_tuner is not None:
            self.tuned = self.tuned + (self.bw_tuner.cap(),)
            # joint (algorithm, bitwidth) tuner only: the fourth tuned
            # field carries the collective algorithm for the traffic class
            # in flight; the plain BitwidthTuner has no algorithm axis and
            # the frame stays byte-identical to the 3-field wire
            algo = getattr(self.bw_tuner, "algorithm", None)
            if algo is not None:
                self.tuned = self.tuned + (algo(),)
        return self.tuned

    def _negotiate(self, per_rank, seq: int = -1) -> bytes:
        flags = 0
        self.last_negotiation = time.monotonic()
        if self.on_negotiate is not None:
            # fault hook (die@coordinator / slow@coordinator): runs under
            # self.cv by design — a brownout here stalls every rank, which
            # is exactly the failure being modeled
            self.on_negotiate()
        tuned = self._tune()
        # grouped tier deposits recorded their evicted cache ids under the
        # seq as they arrived (exchange_tier holds no per-rank rows to
        # re-walk here)
        invalid: set = self.ginvalid.pop(seq, set())
        for rank, (rflags, cached, reqs) in per_rank.items():
            if rflags & wire.REQ_JOIN:
                if rank not in self.joined:
                    self.joined.add(rank)
                    self.last_joined = rank
            for cid in cached:
                m = self._meta_of(rank, cid)
                if m is not None:
                    self.cache_hits += 1
                    instruments.response_cache_hits().inc()
                    if m.name in self.cache_ids:
                        self.cache_ids.move_to_end(m.name)
                    self._add(rank, m)
                else:
                    # the id was evicted (LRU churn or stall invalidation)
                    # after this rank cached it: report it in invalid_ids so
                    # the rank forgets it and resubmits full metadata
                    invalid.add(cid)
                    self.cache_misses += 1
                    instruments.response_cache_misses().inc()
            for m in reqs:
                self.cache_misses += 1
                instruments.response_cache_misses().inc()
                self._add(rank, m)

        # straggler escalation: an excluded rank that has trailed the
        # negotiation frontier by more than max_skip rounds is promoted
        # away — declared lost (same reset path a dropped connection takes)
        # and its host reported to the elastic driver so a hot spare is
        # admitted at the next commit boundary
        excl: set = set()
        if self.straggler is not None:
            if seq >= 0:
                for r in self.straggler.on_negotiate(seq, self.members):
                    host = self.rank_hosts.get(r, "?")
                    reason = (f"straggler escalation: rank {r} (host {host}) "
                              f"trailed more than "
                              f"{self.straggler.max_skip} rounds while "
                              f"excluded")
                    logger.warning("coordinator: %s", reason)
                    instruments.straggler_promotions().inc()
                    _blackbox.record(_blackbox.K_EXCLUDED, "rank_%d" % r,
                                     "escalated host=%s" % host)
                    self._notify_driver_failure(host, reason)
                    self.rank_lost(r, reason)
                    return self._ranks_changed_bytes()
            excl = set(self.straggler.excluded)

        now = time.monotonic()
        # the common static round has no joiners and no exclusions: alias
        # the member set rather than copying it — at 100k ranks the copy
        # alone was milliseconds per round, dominating grouped (O(groups))
        # negotiation. Nothing below mutates ``active`` in place.
        if self.joined or excl:
            active = set(self.members) - self.joined - excl
        else:
            active = self.members
        epoch = self.epoch if self.elastic else -1
        emembers = sorted(self.members) if self.elastic else None
        wexcl = sorted(excl) if excl else None

        # join barrier: all ranks joined and nothing pending
        # (`controller.cc:202-256`)
        if not active and not self.table:
            flags |= wire.RESP_JOIN_RELEASE
            last = self.last_joined
            self.joined.clear()
            self.last_joined = -1
            return wire.encode_response_list(flags, last, [], [], [],
                                             tuned=tuned, epoch=epoch,
                                             members=emembers,
                                             invalid_ids=sorted(invalid),
                                             excluded=wexcl)

        ready: List[str] = []
        warnings: List[str] = []
        timed_out: List[Tuple[str, List[int], float]] = []
        n_stalled = 0
        max_skew = -1.0
        for name, p in sorted(self.table.items(),
                              key=lambda kv: kv[1].order_idx):
            have = set(p.metas)
            if p.gcount:
                # grouped deposits are counted, not enumerated: the tensor
                # is ready when grouped coverage plus flat per-rank
                # deposits span the active set (group membership is
                # all-or-nothing per payload, so the count is exact; under
                # straggler exclusion this conservatively counts an
                # excluded-but-deposited rank, which only ever completes a
                # tensor the active set already agreed on)
                flat_have = {r for r in have if r not in p.grouped}
                tensor_ready = (p.gcount + len(flat_have & active)
                                >= len(active))
            else:
                tensor_ready = active <= have
            if tensor_ready:
                ready.append(name)
                if len(p.arrivals) > 1:
                    max_skew = max(max_skew, max(p.arrivals.values())
                                   - min(p.arrivals.values()))
                # completed: re-arm the stall inspector so a second stall of
                # the same tensor warns again
                self.warned.discard(name)
                continue
            waited = now - p.first_t
            missing = sorted(active - have)
            if (self.collective_timeout_s
                    and waited > self.collective_timeout_s):
                if self.elastic and all(r > 0 for r in missing):
                    # counted here because no ERROR response reaches the
                    # engines: the reset speaks RESP_RANKS_CHANGED instead
                    instruments.collective_timeouts().inc()
                    # the unresponsive ranks are treated as lost: the
                    # membership reset releases every blocked barrier with
                    # RESP_RANKS_CHANGED, feeding the same re-rendezvous
                    # path a dropped connection would (docs/elastic.md).
                    # Rank 0 hosts this coordinator and cannot be dropped;
                    # a timeout naming it falls through to the error path.
                    logger.warning(
                        "coordinator: collective timeout on tensor '%s' "
                        "(waited %ds on ranks %s); declaring them lost",
                        name, int(waited), missing)
                    _blackbox.record(
                        _blackbox.K_TIMEOUT, name,
                        "waited %ds on ranks %s; declaring them lost"
                        % (int(waited), missing))
                    for r in missing:
                        self.rank_lost(
                            r, f"collective timeout: tensor '{name}' "
                               f"waited {int(waited)}s "
                               f"(HOROVOD_COLLECTIVE_TIMEOUT="
                               f"{self.collective_timeout_s:g}s exceeded)")
                    return self._ranks_changed_bytes()
                timed_out.append((name, missing, waited))
                _blackbox.record(
                    _blackbox.K_TIMEOUT, name,
                    "waited %ds on ranks %s (HOROVOD_COLLECTIVE_TIMEOUT="
                    "%gs exceeded)" % (int(waited), missing,
                                       self.collective_timeout_s))
                self.warned.discard(name)
                # invalidate like a stall: the next negotiation of this
                # name must start from full metadata
                stale_cid = self.cache_ids.pop(name, None)
                if stale_cid is not None:
                    self.cache_meta.pop(stale_cid, None)
                continue
            if waited > self.stall_warning_s:
                n_stalled += 1
            if waited > self.stall_warning_s and name not in self.warned:
                self.warned.add(name)
                warnings.append(
                    f"{name} (waiting on ranks {missing} for {int(waited)}s)")
                _blackbox.record(
                    _blackbox.K_STALL, name,
                    f"waiting on ranks {missing} for {int(waited)}s")
                # stall invalidation: drop the stalled tensor's cache entry
                # so every rank renegotiates it from full metadata once the
                # stall clears (a stale per-rank meta here could otherwise
                # mask the divergence that caused the stall)
                stale_cid = self.cache_ids.pop(name, None)
                if stale_cid is not None:
                    self.cache_meta.pop(stale_cid, None)
            if self.stall_shutdown_s and waited > self.stall_shutdown_s:
                flags |= wire.RESP_SHUTDOWN
                if not self.shutdown_reason:
                    self.shutdown_reason = (
                        f"stall shutdown: tensor '{name}' waited {int(waited)}"
                        f"s on ranks {missing} (HOROVOD_STALL_SHUTDOWN_TIME_"
                        "SECONDS exceeded, stall_inspector.h:80)")
                    _blackbox.record(_blackbox.K_ERROR, "shutdown",
                                     self.shutdown_reason)

        instruments.stalled_tensors().set(n_stalled)
        if max_skew >= 0:
            instruments.straggler_skew_seconds().set(max_skew)

        singles = []
        responses: List[Response] = []
        assignments: List[List[int]] = []
        for name, missing, waited in timed_out:
            self.table.pop(name, None)
            responses.append(Response(
                ResponseType.ERROR, [name],
                error_message=(
                    f"collective timeout: tensor '{name}' waited "
                    f"{int(waited)}s on ranks {missing} "
                    f"(HOROVOD_COLLECTIVE_TIMEOUT="
                    f"{self.collective_timeout_s:g}s exceeded)")))
            assignments.append([-1])
        for name in ready:
            p = self.table.pop(name)
            err = self._validate(name, p.metas, active)
            if err is not None:
                resp = Response(ResponseType.ERROR, [name], error_message=err)
                responses.append(resp)
                assignments.append([-1])
                continue
            singles.append((name, p))

        # fusion over negotiated requests (`controller.cc:626-750`): bucket
        # same-signature tensors under the threshold; deterministic because it
        # runs once at the coordinator
        used = [False] * len(singles)
        for i, (name, p) in enumerate(singles):
            if used[i]:
                continue
            used[i] = True
            m0 = p.metas[min(p.metas)]
            bucket = [i]
            total = self._nbytes(m0)
            if int(m0.rtype) in _FUSABLE:
                for j in range(i + 1, len(singles)):
                    if used[j]:
                        continue
                    mj = singles[j][1].metas[min(singles[j][1].metas)]
                    if (self._fuse_sig(mj) == self._fuse_sig(m0)
                            and total + self._nbytes(mj) <= self.threshold):
                        used[j] = True
                        bucket.append(j)
                        total += self._nbytes(mj)
            resp = Response(ResponseType(int(m0.rtype)),
                            [singles[k][0] for k in bucket],
                            average=m0.average)
            resp.prescale = m0.prescale
            resp.postscale = m0.postscale
            resp.root_rank = m0.root_rank
            resp.tensor_dtype = m0.dtype
            resp.compression = self._resolve_compression(
                [m for k in bucket for m in singles[k][1].metas.values()])
            cids: List[int] = []
            for k in bucket:
                kname, pk = singles[k]
                mk0 = pk.metas.get(0, pk.metas[min(pk.metas)])
                resp.tensor_shapes.append(tuple(mk0.shape))
                if int(m0.rtype) == int(RequestType.ALLGATHER):
                    resp.tensor_sizes.append(
                        [int(pk.metas[r].shape[0]) if r in pk.metas else 0
                         for r in range(self.world)])
                elif (int(m0.rtype) == int(RequestType.ALLTOALL)
                        and mk0.splits is not None):
                    # ragged alltoall: the full world x world send matrix,
                    # row-major by source rank — the executor's alltoallv
                    # displacement table, the role Response::tensor_sizes
                    # plays for ragged allgather. Every rank is present:
                    # alltoall+join is rejected in _validate, so a ready
                    # ragged alltoall has a meta from all of them.
                    mat: List[int] = []
                    for r in range(self.world):
                        mat.extend(int(s) for s in pk.metas[r].splits)
                    resp.tensor_sizes.append(mat)
                cids.append(self._assign_cache_id(kname, pk.metas))
            responses.append(resp)
            assignments.append(cids)
        if responses:
            instruments.negotiations().inc()
        return wire.encode_response_list(flags, self.last_joined, responses,
                                         assignments, warnings,
                                         self.shutdown_reason, tuned=tuned,
                                         epoch=epoch, members=emembers,
                                         invalid_ids=sorted(invalid),
                                         excluded=wexcl)

    def _add(self, rank: int, m: ReqMeta) -> None:
        if (self.tuner is not None and self.bw_tuner is None
                and m.compression.startswith("adaptive")):
            from ..ops import adaptive as _adaptive

            # HOROVOD_AUTOTUNE_ALGO upgrades the bitwidth-cap search to the
            # joint (algorithm, bitwidth) tuner (autotune v3); unset keeps
            # the PR 10 cap-only walk and the 3-field tuned broadcast
            if os.environ.get("HOROVOD_AUTOTUNE_ALGO", "").strip() not in (
                    "", "0", "false", "off"):
                self.bw_tuner = _adaptive.JointTuner()
            else:
                self.bw_tuner = _adaptive.BitwidthTuner()
        p = self.table.get(m.name)
        if p is None:
            p = _Pending(self.order_ctr)
            self.order_ctr += 1
            self.table[m.name] = p
        p.metas[rank] = m
        p.arrivals.setdefault(rank, time.monotonic())

    @staticmethod
    def _nbytes(m: ReqMeta) -> int:
        import numpy as np

        n = 1
        for d in m.shape:
            n *= int(d)
        try:
            return n * np.dtype(m.dtype).itemsize
        except TypeError:
            return n * 2  # bfloat16 and friends

    @staticmethod
    def _resolve_compression(metas) -> str:
        """The negotiated wire mode for a bucket. Identical proposals pass
        through unchanged; mismatched ``adaptive:<mode>`` proposals (a
        decision boundary racing the enqueue — _validate admits only this
        kind of mismatch) resolve to the LEAST aggressive grid, so no rank
        is ever forced below the precision it asked for."""
        wires = {m.compression for m in metas}
        if len(wires) == 1:
            return wires.pop()
        order = {"adaptive:int4": 0, "adaptive:int8": 1, "adaptive:bf16": 2}
        return max(wires, key=lambda w: order.get(w, 2))

    @staticmethod
    def _fuse_sig(m: ReqMeta):
        # compression in the key: a quantized bucket compiles a different
        # wire program, so plain and quantized tensors never share a bucket
        return (m.rtype, m.dtype, m.average, m.prescale, m.postscale,
                m.root_rank, m.compression)

    def _assign_cache_id(self, name: str, metas: Dict[int, ReqMeta]) -> int:
        cid = self.cache_ids.get(name)
        if cid is None:
            if self.cache_capacity <= 0:
                return -1
            while len(self.cache_ids) >= self.cache_capacity:
                # evict the least recently negotiated name; workers holding
                # its id learn via invalid_ids on their next submission
                _, evicted = self.cache_ids.popitem(last=False)
                self.cache_meta.pop(evicted, None)
            cid = self.next_cache_id
            self.next_cache_id += 1
            self.cache_ids[name] = cid
            self.cache_meta[cid] = {}
        else:
            self.cache_ids.move_to_end(name)
        # refresh each participating rank's meta (a rank whose params changed
        # arrives here via the full-metadata path and is re-recorded)
        self.cache_meta[cid].update(metas)
        return cid

    # ---- cross-rank validation (`controller.cc:358-597` ConstructResponse)
    def _validate(self, name: str, metas: Dict[int, ReqMeta],
                  active: set) -> Optional[str]:
        items = sorted(metas.items())
        r0, m0 = items[0]
        for r, m in items[1:]:
            if m.rtype != m0.rtype:
                return (f"Mismatched collective operations for tensor "
                        f"'{name}': rank {r0} requested "
                        f"{RequestType(m0.rtype).name}, rank {r} requested "
                        f"{RequestType(m.rtype).name}.")
            if m.dtype != m0.dtype:
                return (f"Mismatched data types for tensor '{name}': rank "
                        f"{r0} has {m0.dtype}, rank {r} has {m.dtype}.")
            if (m.average, m.prescale, m.postscale) != (
                    m0.average, m0.prescale, m0.postscale):
                return ("Mismatched reduction op/scale factors for tensor "
                        f"'{name}' between ranks {r0} and {r}.")
            if m.compression != m0.compression:
                # adaptive wire: a bitwidth-decision boundary can race the
                # enqueue, so two ranks may transiently propose different
                # "adaptive:<mode>" grids — negotiation resolves to the
                # least aggressive (see _resolve_compression), NOT an
                # error. Any other mismatch (static modes, or adaptive on
                # one rank only) is still a config error and fails fast.
                if (m.compression.startswith("adaptive:")
                        and m0.compression.startswith("adaptive:")):
                    continue
                return (f"Mismatched compression for tensor '{name}': rank "
                        f"{r0} requested "
                        f"'{m0.compression or 'none'}', rank {r} requested "
                        f"'{m.compression or 'none'}' (set "
                        "HOROVOD_COMPRESSION identically on every rank).")
        rt = int(m0.rtype)
        a2a_ragged = (rt == int(RequestType.ALLTOALL)
                      and m0.splits is not None)
        if rt in (int(RequestType.ALLREDUCE), int(RequestType.ADASUM),
                  int(RequestType.BROADCAST)) or (
                rt == int(RequestType.ALLTOALL) and not a2a_ragged):
            for r, m in items[1:]:
                if m.shape != m0.shape:
                    return (f"Mismatched tensor shapes for '{name}': rank "
                            f"{r0} has {tuple(m0.shape)}, rank {r} has "
                            f"{tuple(m.shape)}.")
        if rt == int(RequestType.ALLGATHER):
            if any(len(m.shape) == 0 for _, m in items):
                return f"Allgather of scalar tensor '{name}' is not supported."
            for r, m in items[1:]:
                if m.shape[1:] != m0.shape[1:]:
                    return ("Mismatched allgather tensor shapes beyond first "
                            f"dimension for '{name}': rank {r0} has "
                            f"{tuple(m0.shape)}, rank {r} has "
                            f"{tuple(m.shape)}.")
        if rt == int(RequestType.ADASUM) and (self.world & (self.world - 1)):
            return (f"Adasum requires a power-of-2 number of ranks; got "
                    f"{self.world}.")
        if rt == int(RequestType.ALLTOALL):
            for r, m in items:
                if (m.splits is None) != (m0.splits is None):
                    return (f"Mismatched alltoall splits usage for tensor "
                            f"'{name}': rank {r0} "
                            f"{'passed' if a2a_ragged else 'omitted'} "
                            f"splits, rank {r} did not match.")
            if a2a_ragged:
                for r, m in items:
                    if not m.shape:
                        return (f"Alltoall of scalar tensor '{name}' is "
                                "not supported.")
                    if len(m.splits) != self.world:
                        return (f"Alltoall splits for tensor '{name}' on "
                                f"rank {r} has {len(m.splits)} entries; "
                                f"expected world size {self.world}.")
                    if any(s < 0 for s in m.splits):
                        return (f"Alltoall splits for tensor '{name}' on "
                                f"rank {r} contains a negative entry.")
                    if sum(m.splits) != m.shape[0]:
                        return (f"Alltoall splits for tensor '{name}' on "
                                f"rank {r} sum to {sum(m.splits)} but dim 0 "
                                f"is {m.shape[0]}.")
                    if m.shape[1:] != m0.shape[1:]:
                        return ("Mismatched alltoall tensor shapes beyond "
                                f"first dimension for '{name}': rank {r0} "
                                f"has {tuple(m0.shape)}, rank {r} has "
                                f"{tuple(m.shape)}.")
            else:
                d0 = m0.shape[0] if m0.shape else 0
                if not m0.shape or d0 % self.world != 0:
                    return (f"Alltoall tensor '{name}' first dimension "
                            f"({d0}) must be divisible by world size "
                            f"{self.world}.")
        if rt == int(RequestType.BROADCAST):
            for r, m in items[1:]:
                if m.root_rank != m0.root_rank:
                    return (f"Mismatched root ranks for broadcast '{name}': "
                            f"rank {r0} says {m0.root_rank}, rank {r} says "
                            f"{m.root_rank}.")
            if self.elastic:
                if m0.root_rank not in self.members:
                    return (f"Invalid root rank {m0.root_rank} for broadcast "
                            f"'{name}' (current members "
                            f"{sorted(self.members)}).")
            elif not (0 <= m0.root_rank < self.world):
                return (f"Invalid root rank {m0.root_rank} for broadcast "
                        f"'{name}' (world size {self.world}).")
        if self.joined and rt in (int(RequestType.ALLGATHER),
                                  int(RequestType.BROADCAST),
                                  int(RequestType.ALLTOALL)):
            # parity: allgather/broadcast unsupported with join
            # (`controller.cc:434-437,510-513`)
            return (f"{RequestType(rt).name} is not supported while a rank "
                    "has joined.")
        return None

    def cache_stats(self) -> Tuple[int, int]:
        with self.cv:
            return self.cache_hits, self.cache_misses

    def health_summary(self) -> dict:
        """Control-plane liveness snapshot for the /healthz endpoint
        (docs/observability.md)."""
        with self.cv:
            age = (round(time.monotonic() - self.last_negotiation, 3)
                   if self.last_negotiation else None)
            return {
                "world_size": self.world,
                "members": sorted(self.members),
                "epoch": self.epoch,
                "elastic": self.elastic,
                "shutting_down": self.bye,
                "shutdown_reason": self.shutdown_reason,
                "fenced": self.fenced,
                "fence_reason": self.fence_reason,
                "last_negotiation_age_s": age,
                "disconnected": {str(r): why for r, (_, why)
                                 in self.disconnected.items()},
                "heartbeat_misses": {str(r): n for r, n
                                     in self._hb_miss_counts.items() if n},
                "silent_ranks": sorted(self._hb_silent),
                "excluded_ranks": (sorted(self.straggler.excluded)
                                   if self.straggler is not None else []),
                "straggler_episodes": (
                    {str(r): n for r, n in self.straggler.episodes.items()}
                    if self.straggler is not None else {}),
            }


class CoordinatorServer:
    """TCP front-end for :class:`CoordState`; one handler thread per worker."""

    def __init__(self, state: CoordState, secret: str, host: str = "0.0.0.0",
                 local_rank: int = 0):
        self.state = state
        self.secret = secret
        self._stop = threading.Event()
        # fencing epoch this coordinator HOLDS (its lease epoch); stamped on
        # every outgoing frame. 0 = lease-based leadership off, which keeps
        # every frame byte-identical to the pre-fencing wire format. A
        # fenced coordinator keeps stamping its last-held epoch, which is
        # exactly what lets receivers following a newer one reject it.
        self.fence_epoch = 0
        # coordinator-side fault injection in the hosting process (rank 0,
        # or the standby's rank after a promotion); die@coordinator /
        # slow@coordinator fire per negotiation round
        self._faults = faultinject.for_rank(local_rank)
        if self._faults is not None:
            state.on_negotiate = self._negotiation_fault
        # per-rank connection generation: a serve thread that loses its
        # connection reports the loss only if no newer connection has taken
        # over the rank — a stale thread unblocking late must not re-mark a
        # reconnected rank as disconnected
        self._conn_gen: Dict[int, int] = {}
        self._gen_lock = threading.Lock()
        # every accepted connection, tracked so die() can sever them all
        # abruptly (fault injection / standby-failover tests)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        # liveness knobs, read once (docs/fault-tolerance.md)
        self._grace_s = _env_float("HOROVOD_RECONNECT_GRACE", 10.0)
        self._hb_interval = _env_float("HOROVOD_HEARTBEAT_INTERVAL", 5.0)
        self._hb_timeout = _env_float("HOROVOD_HEARTBEAT_TIMEOUT", 0.0)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(max(8, state.world))
        self.port = self._sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="hvd_coord_accept", daemon=True)
        self._accept_thread.start()
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, name="hvd_coord_liveness", daemon=True)
        self._monitor_thread.start()
        # /healthz pulls its control-plane section straight from the state
        # machine (docs/observability.md)
        from ..metrics import set_health_source
        set_health_source(state.health_summary)

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(0.5)
            if self._faults is not None:
                conn = self._faults.wrap(conn)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             name="hvd_coord_conn", daemon=True).start()

    def _negotiation_fault(self) -> None:
        """CoordState.on_negotiate hook: apply die/slow rules at point
        ``coordinator`` (one hit per negotiation round)."""
        for kind, seconds in self._faults.actions_for("coordinator"):
            if kind == "slow":
                time.sleep(seconds)
            elif kind == "die":
                # sever everything off-thread: die() closes sockets, which
                # is safe under state.cv, but never block a negotiation on
                # socket teardown
                threading.Thread(target=self.die, name="hvd_coord_die",
                                 daemon=True).start()

    def die(self) -> None:
        """Abrupt coordinator death (die@coordinator, chaos tests): close
        the listening socket and every accepted connection with no BYE and
        no cleanup — from the workers' side, indistinguishable from
        SIGKILL of rank 0. The state machine is left untouched so an
        in-process rank 0 caller keeps functioning (in the real SIGKILL
        case the whole process is gone anyway)."""
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.5):
            try:
                self.state.check_liveness(self._grace_s, self._hb_interval,
                                          self._hb_timeout)
            except Exception:
                logger.debug("coordinator: liveness sweep failed",
                             exc_info=True)

    def _serve(self, conn) -> None:
        rank = -1
        gen = 0
        seq = 0
        # ranks whose frames ride this connection as a host batch: all of
        # them are disconnected together if the connection dies, and any
        # that vanish from the batched heartbeat died locally at the host
        batch_ranks: set = set()
        # tier subtrees whose frames ride this connection (one per
        # mid-tier aggregator child): connection loss opens the reconnect
        # grace window for every rank they vouch for
        tier_subtrees: Dict[str, list] = {}
        # batch responses are written by per-batch handler threads, so
        # writes to a sub-coordinator connection need serializing
        send_lock = threading.Lock()
        try:
            mt, seq0, rank, payload = wire.recv_frame(conn, self.secret,
                                                      self._stop)
            set_peer = getattr(conn, "set_peer", None)
            if set_peer is not None:
                # partition rules need to know which rank sits on the other
                # end of this accepted connection
                set_peer(rank)
            if self.state.fenced:
                # a fenced coordinator answers every dial — including the
                # promoted standby's replication redial — with FENCED
                # stamped with its last-held epoch, then hangs up
                self._send_fenced(conn, seq0)
                return
            if mt == MSG_REPL_HELLO:
                self._serve_repl(conn, rank,
                                 payload.decode("utf-8", "replace")
                                 if payload else "")
                return
            if mt not in (MSG_HELLO, MSG_RESUME):
                raise ConnectionError(f"expected HELLO/RESUME, got {mt}")
            with self._gen_lock:
                gen = self._conn_gen.get(rank, 0) + 1
                self._conn_gen[rank] = gen
            try:
                # rank -> host for straggler escalation (FaultSocket proxies
                # getpeername); best-effort — a failed lookup only costs the
                # blacklist entry, never the connection
                self.state.note_rank_host(rank, conn.getpeername()[0])
            except OSError:
                pass
            self.state.mark_alive(rank)
            if mt == MSG_RESUME:
                self.state.rank_reconnected(rank,
                                            wire.decode_resume(payload))
            while True:
                mt, seq, rank, payload = wire.recv_frame(conn, self.secret,
                                                         self._stop)
                if self.state.fenced:
                    self._send_fenced(conn, seq)
                    return
                self.state.mark_alive(rank)
                if mt == MSG_BYE:
                    self.state.set_bye()
                    return
                if mt == MSG_HEARTBEAT:
                    # liveness beacon: mark_alive above is the whole effect
                    continue
                if mt == MSG_DATA:
                    data = self.state.data_exchange(rank, payload)
                    wire.send_frame(conn, self.secret, MSG_DATA_RESP, seq, 0,
                                    data, fence=self.fence_epoch)
                    continue
                if mt == MSG_METRICS:
                    # fire-and-forget: store the rank's snapshot for the
                    # /metrics endpoint; no reply frame
                    from ..metrics import store_report

                    try:
                        mrank, ts, snap = wire.decode_metrics_report(payload)
                        store_report(mrank, snap, ts)
                    except Exception:
                        logger.debug("coordinator: bad metrics report from "
                                     "rank %s", rank, exc_info=True)
                    continue
                if mt == MSG_BLACKBOX:
                    # fire-and-forget: a dying worker shipped its flight
                    # recorder; persist it into rank 0's bundle, no reply
                    try:
                        brank, _, doc_json = wire.decode_blackbox_dump(
                            payload)
                        _blackbox.store_dump(brank, doc_json)
                    except Exception:
                        logger.debug("coordinator: bad blackbox dump from "
                                     "rank %s", rank, exc_info=True)
                    continue
                if mt == MSG_TRACE:
                    # fire-and-forget: merge the rank's completed spans into
                    # rank 0's trace store; no reply frame
                    from .. import tracing as _tracing

                    try:
                        _, spans = wire.decode_trace_batch(payload)
                        _tracing.store_batch(spans)
                    except Exception:
                        logger.debug("coordinator: bad trace batch from "
                                     "rank %s", rank, exc_info=True)
                    continue
                if mt == MSG_CKPT_MARK:
                    # fire-and-forget: a member snapshotted its shard
                    try:
                        step, epoch, _index = wire.decode_ckpt_mark(payload)
                        self.state.ckpt_mark(rank, step, epoch)
                    except Exception:
                        logger.debug("coordinator: bad ckpt mark from "
                                     "rank %s", rank, exc_info=True)
                    continue
                if mt == MSG_CKPT_DONE:
                    # fire-and-forget: a member's shard file landed; the
                    # bundle finalizes here once every member is in
                    try:
                        step, epoch, index, nbytes, crc = \
                            wire.decode_ckpt_done(payload)
                        self.state.ckpt_done(rank, step, epoch, index,
                                             nbytes, crc)
                    except Exception:
                        logger.debug("coordinator: bad ckpt done from "
                                     "rank %s", rank, exc_info=True)
                    continue
                if mt == MSG_CLOCK:
                    # clock-offset probe: answer immediately with rank 0's
                    # trace clock and the job trace id (latency here IS the
                    # measurement, so no queuing behind state locks)
                    from .. import tracing as _tracing

                    reply = wire.encode_clock_reply(
                        _tracing.clock.trace_us(), _tracing.ensure_trace_id())
                    wire.send_frame(conn, self.secret, MSG_CLOCK_RESP, seq,
                                    0, reply, fence=self.fence_epoch)
                    continue
                if mt == MSG_BATCH:
                    # one host's aggregated round: answer from a handler
                    # thread — the serve loop must keep draining frames
                    # (heartbeats, the next batch) while barriers block
                    entries = wire.decode_batched_entries(payload)
                    self.state.marks_alive([e[0] for e in entries])
                    batch_ranks.update(e[0] for e in entries)
                    threading.Thread(
                        target=self._handle_batch,
                        args=(conn, seq, entries, send_lock),
                        name="hvd_coord_batch", daemon=True).start()
                    continue
                if mt == MSG_TBATCH:
                    # one tier aggregator's grouped round: same handler
                    # thread rule as MSG_BATCH (barriers must not block the
                    # serve loop), but the state work is O(groups)
                    tier, index, groups = wire.decode_tier_batch(payload)
                    subtree = "t%d.%d" % (tier, index)
                    tier_subtrees.setdefault(subtree, [])
                    threading.Thread(
                        target=self._handle_tier_batch,
                        args=(conn, seq, tier, subtree, groups, send_lock),
                        name="hvd_coord_tbatch", daemon=True).start()
                    continue
                if mt == MSG_THB:
                    tier, index, runs = wire.decode_tier_heartbeat(payload)
                    subtree = "t%d.%d" % (tier, index)
                    prev = tier_subtrees.get(subtree, [])
                    for r in wire.runs_to_ranks(
                            wire.runs_subtract(prev, runs)):
                        if r == rank:
                            continue
                        # the aggregator stopped vouching for this rank:
                        # its leaf connection died somewhere down the tree
                        self.state.rank_disconnected(
                            r, "dropped from tier batch heartbeat "
                               f"(subtree {subtree})")
                    tier_subtrees[subtree] = runs
                    self.state.mark_subtree_alive(subtree, tier, runs)
                    continue
                if mt == MSG_BATCH_HB:
                    alive = wire.decode_batched_heartbeat(payload)
                    self.state.marks_alive(alive)
                    for r in sorted(batch_ranks - set(alive) - {rank}):
                        # the sub-coordinator stopped vouching for this
                        # rank: its local connection died
                        self.state.rank_disconnected(
                            r, "dropped from host batch heartbeat "
                               f"(sub-coordinator rank {rank})")
                    batch_ranks = set(alive) | (batch_ranks & {rank})
                    continue
                if mt != MSG_LIST:
                    raise ConnectionError(f"unexpected message type {mt}")
                data = self.state.exchange(rank, seq, payload)
                wire.send_frame(conn, self.secret, MSG_RESP, seq, 0, data,
                                fence=self.fence_epoch)
        except ShutdownError:
            pass
        except CoordinatorFencedError:
            # the state fenced while this thread was blocked in an
            # exchange/barrier: answer FENCED (best effort) and hang up
            # without opening a reconnect-grace window — a fenced
            # coordinator must not mutate liveness state either
            self._send_fenced(conn, seq)
        except (ConnectionError, OSError) as exc:
            if self._stop.is_set() or rank < 0:
                return
            with self._gen_lock:
                stale = self._conn_gen.get(rank, 0) != gen
            if stale:
                # the rank already resumed on a newer connection; this
                # thread's late error says nothing about current liveness
                return
            logger.warning("coordinator: rank %s connection lost (%s); "
                           "reconnect grace window open", rank, exc)
            self.state.rank_disconnected(rank, str(exc))
            for r in sorted(batch_ranks - {rank}):
                self.state.rank_disconnected(
                    r, f"host batch connection lost ({exc})")
            for subtree in tier_subtrees:
                self.state.subtree_disconnected(subtree, str(exc))
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle_batch(self, conn, frame_seq: int, entries,
                      send_lock) -> None:
        try:
            replies, deferred = self.state.exchange_batch(entries)
            if replies:
                with send_lock:
                    wire.send_frame(conn, self.secret, MSG_BATCH_RESP,
                                    frame_seq, 0,
                                    wire.encode_batched_entries(replies),
                                    fence=self.fence_epoch)
            for rank, seq, payload in deferred:
                # prospective joiners: their admission wait spans member
                # commit rounds, so each gets its own thread and ships as
                # a single-entry response frame whenever it completes
                threading.Thread(
                    target=self._handle_deferred,
                    args=(conn, rank, seq, payload, send_lock),
                    name="hvd_coord_join", daemon=True).start()
        except CoordinatorFencedError:
            with send_lock:
                self._send_fenced(conn, frame_seq)
        except (ConnectionError, OSError, ShutdownError):
            pass  # the serve thread owns connection-loss reporting

    def _handle_tier_batch(self, conn, frame_seq: int, tier: int,
                           subtree: str, groups, send_lock) -> None:
        try:
            replies, deferred = self.state.exchange_tier(tier, subtree,
                                                         groups)
            if replies:
                with send_lock:
                    wire.send_frame(conn, self.secret, MSG_TBATCH_RESP,
                                    frame_seq, 0,
                                    wire.encode_tier_batch_resp(replies),
                                    fence=self.fence_epoch)
            for rank, seq, payload in deferred:
                # prospective joiners drop out of the grouped path: their
                # admission wait spans member commit rounds, so each ships
                # later as a single-entry MSG_BATCH_RESP frame
                threading.Thread(
                    target=self._handle_deferred,
                    args=(conn, rank, seq, payload, send_lock),
                    name="hvd_coord_join", daemon=True).start()
        except CoordinatorFencedError:
            with send_lock:
                self._send_fenced(conn, frame_seq)
        except (ConnectionError, OSError, ShutdownError):
            pass  # the serve thread owns connection-loss reporting

    def _handle_deferred(self, conn, rank: int, seq: int, payload: bytes,
                         send_lock) -> None:
        try:
            data = self.state.exchange(rank, seq, payload)
            with send_lock:
                wire.send_frame(
                    conn, self.secret, MSG_BATCH_RESP, 0, 0,
                    wire.encode_batched_entries([(rank, seq, data)]),
                    fence=self.fence_epoch)
        except CoordinatorFencedError:
            with send_lock:
                self._send_fenced(conn, seq)
        except (ConnectionError, OSError, ShutdownError):
            pass

    def _serve_repl(self, conn, standby_rank: int,
                    subtree: str = "") -> None:
        """Replication shipper (MSG_REPL_HELLO): stream one snapshot plus a
        journal record per epoch change to a warm standby. A clean end
        sends BYE so the standby knows not to promote; an abrupt death
        (SIGKILL, die@coordinator) just drops the stream — which is the
        standby's promotion trigger (docs/control-plane.md). A REPL_HELLO
        payload naming a subtree (``t{tier}.{index}``) scopes the stream to
        that subtree's churn — the per-tier standby path."""
        import queue as _queue

        q: "_queue.Queue" = _queue.Queue()
        self.state.attach_journal(q, subtree)
        lag_tier = (subtree.split(".", 1)[0].lstrip("t") if subtree
                    else "root")
        logger.info("coordinator: standby rank %s attached to the "
                    "replication stream%s", standby_rank,
                    " (subtree %s)" % subtree if subtree else "")
        try:
            while not self._stop.is_set():
                if self.state.fenced:
                    # the stream's truth ends here: a fenced coordinator
                    # must not keep feeding a standby state it no longer
                    # owns. FENCED (not BYE) so the standby knows why.
                    self._send_fenced(conn, 0)
                    return
                try:
                    mt, payload = q.get(timeout=0.5)
                except _queue.Empty:
                    if self.state.bye:
                        break
                    continue
                wire.send_frame(conn, self.secret, mt, 0, 0, payload,
                                fence=self.fence_epoch)
                instruments.standby_journal_lag().labels(
                    tier=lag_tier).set(q.qsize())
            wire.send_frame(conn, self.secret, MSG_BYE, 0, 0,
                            fence=self.fence_epoch)
        except (ConnectionError, OSError):
            pass
        finally:
            self.state.detach_journal(q)

    def _send_fenced(self, conn, seq: int) -> None:
        """Answer a frame from a fenced coordinator: MSG_FENCED stamped with
        the LAST-HELD epoch (receivers following a newer one reject it —
        ticking hvd_frames_fenced_total — and everyone else treats it as a
        dead connection and redials toward the promoted standby)."""
        try:
            wire.send_frame(
                conn, self.secret, MSG_FENCED, seq, 0,
                self.state.fence_reason.encode("utf-8", "replace")[:512],
                fence=self.fence_epoch)
        except (ConnectionError, OSError):
            pass

    def stop(self) -> None:
        self._stop.set()
        from ..metrics import set_health_source
        set_health_source(None)
        try:
            self._sock.close()
        except OSError:
            pass


# ----------------------------------------------------------- address exchange
# Each (rank, init-generation) publishes/resolves under a distinct key so a
# shutdown()+init() cycle in the same processes cannot collide with the
# previous coordinator's stale address. Generations advance identically on
# every rank (one per init).
_GEN_BY_RANK: Dict[int, int] = {}
_GEN_LOCK = threading.Lock()


def _next_gen(rank: int) -> int:
    with _GEN_LOCK:
        n = _GEN_BY_RANK.get(rank, 0)
        _GEN_BY_RANK[rank] = n + 1
        return n


def _publish_key(key: str, addr: str, secret: str) -> None:
    """Publish one address-channel key. Besides the primary ``addr.{gen}``,
    the hierarchical/failover planes use ``addr.{gen}.h{group}`` (a host
    sub-coordinator) and ``addr.{gen}.f{n}`` (the n-th promoted standby)."""
    payload = f"{addr}\n{secret}"
    kv_addr = os.environ.get("HVD_KV_ADDR")
    if kv_addr:
        from ..run.rendezvous import KVStoreClient

        KVStoreClient(kv_addr, os.environ.get("HVD_SECRET", "")).put(
            "hvdcoord", key, payload.encode())
        return
    _jax_kv().key_value_set(f"hvdcoord/{key}", payload)


def _resolve_key(key: str, timeout: float) -> Tuple[str, str]:
    kv_addr = os.environ.get("HVD_KV_ADDR")
    if kv_addr:
        from ..run.rendezvous import KVStoreClient

        client = KVStoreClient(kv_addr, os.environ.get("HVD_SECRET", ""))
        payload = client.wait("hvdcoord", key, timeout=timeout).decode()
    else:
        payload = _jax_kv().blocking_key_value_get(f"hvdcoord/{key}",
                                                   int(timeout * 1000))
    addr, _, secret = payload.partition("\n")
    return addr, secret


def _publish(gen: int, addr: str, secret: str) -> None:
    _publish_key(f"addr.{gen}", addr, secret)


def _resolve(gen: int, timeout: float) -> Tuple[str, str]:
    return _resolve_key(f"addr.{gen}", timeout)


def has_address_channel() -> bool:
    """True when some channel exists to exchange the coordinator address —
    and therefore every rank will reach the same conclusion (the launcher env
    and jax.distributed state are identical across ranks). Engine setup fails
    hard if the channel exists but the plane cannot come up: a silent
    per-rank fallback would leave ranks on different control planes and hang
    the job."""
    if os.environ.get("HVD_KV_ADDR"):
        return True
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False


def _jax_kv():
    """Fallback address channel when no launcher KV exists: the
    jax.distributed coordinator's KV service (same service the TPU runtime
    uses for its own bootstrap)."""
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError("no HVD_KV_ADDR and jax.distributed not "
                           "initialized: cannot exchange coordinator address")
    return client


# ------------------------------------------------------------------ controller
class _LocalReq:
    __slots__ = ("meta", "handle", "cached_id")

    def __init__(self, meta: ReqMeta, handle: int, cached_id: int):
        self.meta = meta
        self.handle = handle
        self.cached_id = cached_id


class CoordController:
    """Controller implementation over the cross-process plane.

    Engine-facing interface matches NativeController/PyController; internally
    every tick performs one gather/bcast exchange with rank 0 (the reference
    does the same over MPI every cycle, `mpi_controller.cc:107-161`).
    """

    SUBMIT_DUPLICATE = -1
    SUBMIT_SHUTDOWN = -2
    SUBMIT_RANKS_CHANGED = -3
    coordinated = True

    def __init__(self, world: int, fusion_threshold: int,
                 stall_warning_s: float, stall_shutdown_s: float,
                 cache_capacity: int, fusion_enabled: bool,
                 timeline_path: Optional[str], autotune: bool,
                 cycle_time_ms: float, local_only: bool = False,
                 self_rank: int = 0, start_timeout: float = 120.0):
        self._world = world
        self._rank = self_rank
        self._threshold = fusion_threshold if fusion_enabled else 0
        self._cycle_ms = cycle_time_ms
        self._timeline = Timeline(timeline_path)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._seq = 0
        self._next_handle = 0
        self._outbox: List[_LocalReq] = []
        self._inflight: Dict[str, _LocalReq] = {}  # name -> pending request
        self._sig_cache: Dict[Tuple, int] = {}
        self._hits = 0
        self._misses = 0
        self._join_handle: Optional[int] = None
        self._join_announced = False
        self._bye_sent = False
        self._send_lock = threading.Lock()
        # autotune: scores buffer locally between ticks and ride the next
        # request frame; tuned params come back in every ResponseList
        self._autotune = autotune
        self._score_bytes = 0
        self._score_busy = 0.0
        self._score_epoch: Optional[float] = None
        # ---- fault tolerance (docs/fault-tolerance.md)
        self._faults = faultinject.for_rank(self_rank)
        # ---- fenced leadership (runtime/lease.py): the guard tracks the
        # highest fencing epoch this worker has observed and rejects frames
        # from deposed coordinators; the lease handle exists on rank 0 only
        # (and only with HOROVOD_LEASE_TTL set)
        self._guard = wire.FenceGuard(rank=self_rank)
        self._lease = None
        self._last_acked = -1  # highest seq whose response fully arrived
        self._reconnect_attempts = int(
            _env_float("HOROVOD_RECONNECT_ATTEMPTS", 8))
        self._reconnect_backoff = _env_float("HOROVOD_RECONNECT_BACKOFF",
                                             0.05)
        self._reconnect_backoff_max = _env_float(
            "HOROVOD_RECONNECT_BACKOFF_MAX", 2.0)
        self._hb_interval = _env_float("HOROVOD_HEARTBEAT_INTERVAL", 5.0)
        # ---- elastic membership (docs/elastic.md)
        self._elastic = os.environ.get("HVD_ELASTIC", "") not in ("", "0")
        self._epoch = 0 if self._elastic else -1
        self._members: List[int] = list(range(world))
        # set while a membership reset is unacknowledged: every submit fails
        # with SUBMIT_RANKS_CHANGED until ElasticState.sync() calls resume(),
        # so no survivor can silently keep training against a stale epoch
        self._ranks_changed_reason: Optional[str] = None
        self._commit_pending = False
        self._dseq = 0
        # ---- straggler exclusion (runtime/straggler.py): the excluded set
        # the coordinator broadcast in the last ResponseList, and the actual
        # contributor list of the last partial data exchange (None on full
        # rounds) — ElasticExecutor reads the latter for EF residual
        # accounting
        self._excluded: frozenset = frozenset()
        self.last_data_contributors: Optional[List[int]] = None
        # ---- survivable control plane (docs/control-plane.md)
        self._hier = os.environ.get(
            "HOROVOD_HIERARCHICAL_COORD", "") not in ("", "0")
        self._standby_enabled = os.environ.get(
            "HOROVOD_STANDBY_COORD", "") not in ("", "0")
        self._reconnect_jitter = _env_float("HOROVOD_RECONNECT_JITTER", 0.0)
        self._fo = 0  # how many failovers this worker has followed
        self._subcoord = None       # per-host sub-coordinator (host leaders)
        self._standby_coord = None  # warm-standby replica (rank 1)
        # N-tier mode (HOROVOD_HIERARCHY_TIERS >= 2): mid-tier aggregators
        # and tier standbys this host leader owns (docs/control-plane.md)
        self._tier_aggs: List = []
        self._tier_standbys: List = []
        # hierarchical mode: bulk DATA/CLOCK frames bypass the
        # sub-coordinator on a lazily-dialed direct connection to rank 0
        self._direct_sock: Optional[socket.socket] = None
        self._direct_lock = threading.Lock()
        self._direct_send_lock = threading.Lock()
        self._host0, self._port0, self._secret0 = "", 0, ""
        # everything the warm standby needs to rebuild an equivalent
        # CoordState at promotion time (tuner deliberately excluded: the
        # GP/EI restarts cold rather than replicating its posterior)
        self._state_ctor = dict(
            world=world,
            threshold=fusion_threshold if fusion_enabled else 0,
            cache_capacity=cache_capacity,
            stall_warning_s=stall_warning_s,
            stall_shutdown_s=stall_shutdown_s)

        gen = _next_gen(self_rank)
        self._gen = gen
        if self_rank == 0:
            # no launcher secret (jax-KV address path): generate one and ship
            # it over the address channel, so the TCP service never accepts
            # unauthenticated frames
            from ..run.rendezvous import make_secret

            self._secret = os.environ.get("HVD_SECRET") or make_secret()
            tuner = None
            if autotune:
                try:
                    from .native import NativeTuner

                    tuner = NativeTuner(
                        fusion_threshold if fusion_enabled else 0,
                        cycle_time_ms)
                except Exception as exc:
                    logger.warning(
                        "HOROVOD_AUTOTUNE requested but the native GP/EI "
                        "tuner is unavailable (%s); coordinated autotune "
                        "disabled", exc)
                    self._autotune = False
            self._state: Optional[CoordState] = CoordState(
                world, fusion_threshold if fusion_enabled else 0,
                cache_capacity, stall_warning_s, stall_shutdown_s,
                tuner=tuner, elastic=self._elastic)
            advertise = _advertise_host()
            bind = "127.0.0.1" if advertise == "127.0.0.1" else "0.0.0.0"
            self._server: Optional[CoordinatorServer] = CoordinatorServer(
                self._state, self._secret, host=bind)
            from . import lease as _lease
            if _lease.lease_enabled():
                # take the lease BEFORE publishing the address: the first
                # frame any worker receives is already epoch-stamped
                self._lease = _lease.LeaseManager(gen, 0)
                ep = self._lease.acquire_initial()
                self._server.fence_epoch = ep
                self._guard.observe(ep)
                self._lease.start_renewing(self._fence_primary)
            _publish(gen, f"{advertise}:{self._server.port}", self._secret)
            self._sock: Optional[socket.socket] = None
            self._addr = "in-process"
            self._host, self._port = "", 0
            self._host0, self._port0 = "127.0.0.1", self._server.port
            self._secret0 = self._secret
            if self._hier and int(os.environ.get("HVD_LOCAL_RANK",
                                                 "0")) == 0:
                # rank 0 is (almost always) also its host's leader: its
                # sub-coordinator dials the in-process server over loopback
                # so host 0's local ranks use the same uniform path
                self._start_subcoord(gen, "127.0.0.1", self._server.port,
                                     advertise)
        else:
            self._state = None
            self._server = None
            addr, self._secret = _resolve(gen, start_timeout)
            host, port = addr.rsplit(":", 1)
            self._host0, self._port0 = host, int(port)
            self._secret0 = self._secret
            if self._hier:
                # host leaders bring up the per-host sub-coordinator, then
                # EVERY local rank (leader included) dials it instead of
                # rank 0 — the leader's aggregator batches the whole host
                # into one upstream frame per round
                local_rank = int(os.environ.get("HVD_LOCAL_RANK",
                                                str(self_rank)))
                group = os.environ.get("HVD_CROSS_RANK", "0")
                if local_rank == 0:
                    self._start_subcoord(gen, host, int(port),
                                         _advertise_host())
                addr, self._secret = _resolve_key(
                    f"addr.{gen}.h{group}", start_timeout)
                host, port = addr.rsplit(":", 1)
            # retained so the reconnect path can re-dial after a drop and so
            # connection-loss errors can say who was unreachable
            self._addr = addr
            self._host, self._port = host, int(port)
            if self._faults is not None:
                self._faults.fire("connect")
                self._faults.set_drop_callback(self._drop_connection)
            deadline = time.monotonic() + start_timeout
            last: Optional[Exception] = None
            while True:
                try:
                    self._sock = socket.create_connection(
                        (host, int(port)), timeout=5)
                    break
                except OSError as exc:
                    last = exc
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"cannot reach coordinator at {addr}: {last}")
                    time.sleep(0.2)
            self._sock.settimeout(0.5)
            if self._faults is not None:
                self._sock = self._faults.wrap(self._sock)
                # partition attribution: this socket talks to rank 0
                self._sock.set_peer(0)
            wire.send_frame(self._sock, self._secret, MSG_HELLO, 0,
                            self_rank)
            # trace clock handshake before the heartbeat thread exists: the
            # socket is quiet, so probe RTTs measure the wire, not queuing
            from .. import tracing as _tracing
            if _tracing.active() is not None:
                try:
                    self._sync_trace_clock()
                except Exception:
                    logger.debug("trace clock sync failed; spans stay in "
                                 "the local timebase", exc_info=True)
            if self._hb_interval > 0:
                threading.Thread(target=self._heartbeat_loop,
                                 name="hvd_heartbeat", daemon=True).start()
            if self._standby_enabled and self_rank == 1:
                if not self._elastic:
                    logger.warning(
                        "HOROVOD_STANDBY_COORD needs HVD_ELASTIC=1 (failover"
                        " is a membership reset); standby disabled")
                else:
                    from .standby import StandbyCoordinator

                    self._standby_coord = StandbyCoordinator(
                        rank=self_rank, gen=gen, host=self._host0,
                        port=self._port0, secret=self._secret0,
                        make_state=self._make_standby_state,
                        should_promote=lambda: not self._stop.is_set())
                    self._standby_coord.start()

    # ------------------------------------------------------------- engine API
    def submit(self, entry: TensorTableEntry) -> int:
        with self._lock:
            if self._stop.is_set():
                return self.SUBMIT_SHUTDOWN
            if self._ranks_changed_reason is not None:
                return self.SUBMIT_RANKS_CHANGED
            if entry.tensor_name in self._inflight:
                return self.SUBMIT_DUPLICATE
            meta = ReqMeta(entry.tensor_name, int(entry.request_type),
                           str(entry.array.dtype), tuple(entry.array.shape),
                           entry.root_rank, entry.average,
                           entry.prescale_factor, entry.postscale_factor,
                           splits=entry.splits,
                           compression=entry.compression)
            cid = self._sig_cache.get(meta.sig(), -1)
            if cid >= 0:
                self._hits += 1
            else:
                self._misses += 1
            h = self._next_handle
            self._next_handle += 1
            req = _LocalReq(meta, h, cid)
            self._inflight[entry.tensor_name] = req
            self._outbox.append(req)
            self._timeline.negotiate_start(entry.tensor_name, self._rank)
            return h

    def join(self, rank: int) -> int:
        with self._lock:
            if self._stop.is_set():
                return self.SUBMIT_SHUTDOWN
            if self._join_handle is None:
                self._join_handle = self._next_handle
                self._next_handle += 1
                self._join_announced = False
            return self._join_handle

    def tick(self):
        if self._stop.is_set():
            raise ShutdownError("control plane shut down")
        if self._faults is not None:
            self._faults.fire("tick")
        with self._lock:
            outbox, self._outbox = self._outbox, []
            flags = 0
            if self._join_handle is not None and not self._join_announced:
                flags |= wire.REQ_JOIN
                self._join_announced = True
            if self._commit_pending:
                flags |= wire.REQ_COMMIT
                self._commit_pending = False
            cached = [r.cached_id for r in outbox if r.cached_id >= 0]
            fresh = [r.meta for r in outbox if r.cached_id < 0]
            seq = self._seq
            self._seq += 1
            epoch = self._epoch
            score = None
            if self._autotune and self._score_bytes > 0:
                # wall interval since the first buffered op: unlike pure busy
                # time, it sees negotiation + cycle latency, which is exactly
                # what the cycle-time knob trades off
                wall = (time.monotonic() - self._score_epoch
                        if self._score_epoch is not None else 0.0)
                score = (self._score_bytes, max(self._score_busy, wall))
                self._score_bytes = 0
                self._score_busy = 0.0
                self._score_epoch = None
        payload = wire.encode_request_list(flags, cached, fresh, score=score,
                                           epoch=epoch)
        try:
            data = self._exchange(seq, payload)
        except (ConnectionError, OSError) as exc:
            # _exchange already retried through the reconnect path; landing
            # here means the loss is unrecoverable — say exactly where the
            # control plane died (satellite of docs/fault-tolerance.md)
            raise ShutdownError(
                f"control-plane connection lost (coordinator {self._addr}, "
                f"rank {self._rank}, last sent seq {seq}, last acked seq "
                f"{self._last_acked}, errno={getattr(exc, 'errno', None)}: "
                f"{exc!r})")
        (rflags, last_joined, responses, assignments, warnings, reason,
         tuned, repoch, rmembers,
         invalid_ids, excluded) = wire.decode_response_list(data)
        if rflags & wire.RESP_RANKS_CHANGED:
            self._apply_ranks_changed(repoch, rmembers or [], reason)
        self._apply_excluded(excluded)
        for resp in responses:
            resp.epoch = repoch
        if tuned is not None:
            # apply the coordinator's broadcast (threshold, cycle_time):
            # every rank moves to the same parameters at the same tick; the
            # engine re-reads cycle_time_ms() after each coordinated tick
            self._threshold = int(tuned[0])
            self._cycle_ms = float(tuned[1])
            if len(tuned) > 2 and tuned[2]:
                # third field: the autotuned bitwidth cap for the adaptive
                # wire — every rank's selector respects it from this tick
                from ..ops import adaptive as _adaptive

                _adaptive.set_autotuned_cap(tuned[2])
                if len(tuned) > 3 and tuned[3]:
                    # fourth field: the joint tuner's collective algorithm
                    # — spmd "auto" steps and the executor follow it
                    _adaptive.set_autotuned_algorithm(tuned[3])
        if rflags & wire.RESP_SHUTDOWN:
            if reason.startswith("stall shutdown"):
                # abnormal abort: surface loudly (parity with the in-process
                # stall-shutdown RuntimeError path)
                raise RuntimeError(reason)
            raise ShutdownError(reason or "coordinated shutdown")

        handle_pairs: List[List[Tuple[int, int]]] = []
        join_released: List[int] = []
        with self._lock:
            if invalid_ids:
                # the coordinator evicted these cache ids (LRU churn or
                # stall invalidation): forget them and resubmit the affected
                # requests with full metadata on the next tick
                dead = set(invalid_ids)
                self._sig_cache = {sig: cid
                                   for sig, cid in self._sig_cache.items()
                                   if cid not in dead}
                for req in self._inflight.values():
                    if req.cached_id in dead:
                        req.cached_id = -1
                        if req not in self._outbox:
                            self._outbox.append(req)
            for resp, cids in zip(responses, assignments):
                pairs: List[Tuple[int, int]] = []
                for name, cid in zip(resp.tensor_names, cids):
                    req = self._inflight.pop(name, None)
                    if req is not None:
                        pairs.append((self._rank, req.handle))
                        # key the cache on THIS rank's request signature
                        # (shapes differ per rank for ragged allgathers)
                        if (cid >= 0
                                and resp.response_type != ResponseType.ERROR):
                            self._sig_cache[req.meta.sig()] = cid
                handle_pairs.append(pairs)
            if rflags & wire.RESP_JOIN_RELEASE and self._join_handle is not None:
                join_released.append(self._join_handle)
                self._join_handle = None
                self._join_announced = False
        if self._rank != 0:
            # the coordinator logs every stall; a WORKER logs only stalls it
            # is itself causing (its rank appears in the missing list), so a
            # lagging rank has local evidence instead of being warn-blind
            warnings = [w for w in warnings if self._stall_names_me(w)]
        if not responses and not join_released and not warnings:
            return None
        return (responses, handle_pairs, join_released, last_joined,
                warnings, False)

    def _apply_excluded(self, excluded) -> None:
        """Track the coordinator's broadcast exclusion set. Logged (and
        blackbox-recorded) only on transitions that involve THIS rank, so a
        straggler host's own log says when it was parked and when it came
        back — the first place an operator looks."""
        from ..goodput import ledger as _goodput

        new = frozenset(excluded or ())
        if new == self._excluded:
            return
        led = _goodput.active()
        if self._rank in new and self._rank not in self._excluded:
            logger.warning(
                "rank %d excluded from collectives by straggler policy "
                "(trailing; contributions accumulate into the EF residual)",
                self._rank)
            _blackbox.record(_blackbox.K_EXCLUDED, "rank_%d" % self._rank,
                             "excluded self", rank=self._rank)
            if led is not None:
                led.note_excluded(self._rank, True)
        elif self._rank in self._excluded and self._rank not in new:
            logger.info("rank %d re-admitted to collectives", self._rank)
            _blackbox.record(_blackbox.K_EXCLUDED, "rank_%d" % self._rank,
                             "readmitted self", rank=self._rank)
            if led is not None:
                led.note_excluded(self._rank, False)
        self._excluded = new

    def excluded_ranks(self) -> frozenset:
        """Ranks currently excluded by the straggler policy (empty when the
        policy is off — the common case)."""
        return self._excluded

    def _stall_names_me(self, warning: str) -> bool:
        """True if this rank is in the warning's 'waiting on ranks [...]'
        list. The suffix is appended by CoordState._negotiate AFTER the
        user-controlled tensor name, so take the LAST pattern match — a
        tensor name containing the same phrase cannot shadow it
        (format coupling pinned by test_stall_names_me_parsing)."""
        ms = re.findall(r"waiting on ranks \[([0-9, ]*)\]", warning)
        if not ms:
            return False
        missing = {int(x) for x in ms[-1].split(",") if x.strip()}
        return self._rank in missing

    def _exchange(self, seq: int, payload: bytes) -> bytes:
        if self._rank == 0:
            assert self._state is not None
            return self._state.exchange(0, seq, payload)
        if self._faults is not None:
            self._faults.fire("exchange")
        data = self._request_reply(MSG_LIST, MSG_RESP, seq, payload)
        self._last_acked = seq
        return data

    def _request_reply(self, msg_type: int, resp_type: int, frame_seq: int,
                       payload: bytes) -> bytes:
        """Worker-side request/reply over the control socket with
        transparent reconnect: on connection loss, re-establish and re-send
        the SAME frame under the SAME seq — the coordinator's replay cache
        makes the retry idempotent (docs/fault-tolerance.md)."""
        while True:
            try:
                sock = self._sock
                assert sock is not None
                with self._send_lock:
                    wire.send_frame(sock, self._secret, msg_type, frame_seq,
                                    self._rank, payload,
                                    fence=self._guard.epoch)
                while True:
                    mt, rseq, _, data = wire.recv_frame(sock, self._secret,
                                                        self._stop,
                                                        guard=self._guard)
                    if mt == MSG_FENCED:
                        # the peer lost its leadership lease: treat like a
                        # connection loss so the reconnect path (and its
                        # failover probing) finds the new leader
                        raise ConnectionError(
                            "coordinator at %s is fenced (%s)" % (
                                self._addr,
                                data.decode("utf-8", "replace")
                                or "lost leadership lease"))
                    if mt == resp_type and rseq == frame_seq:
                        return data
            except (ConnectionError, OSError) as exc:
                if self._stop.is_set():
                    raise ShutdownError("control plane shut down")
                logger.warning("control plane: connection error on seq %s "
                               "(%s); reconnecting to %s",
                               frame_seq, exc, self._addr)
                self._reconnect(exc, frame_seq)

    def _drop_connection(self) -> None:
        """faultinject conn_drop hook: sever the live control connection the
        way a network partition would — the reconnect path must recover."""
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _heartbeat_loop(self) -> None:
        """Off-thread liveness beacon (rank > 0): one MSG_HEARTBEAT every
        HOROVOD_HEARTBEAT_INTERVAL seconds, so the coordinator can tell a
        silently-dead worker from an idle one. Send errors are ignored —
        the exchange path owns reconnects."""
        while not self._stop.wait(self._hb_interval):
            if self._faults is not None:
                self._faults.fire("heartbeat")
            try:
                with self._send_lock:
                    if self._bye_sent or self._sock is None:
                        return
                    wire.send_frame(self._sock, self._secret, MSG_HEARTBEAT,
                                    0, self._rank,
                                    fence=self._guard.epoch)
            except (ConnectionError, OSError):
                pass

    def _reconnect(self, why: Exception, seq: int) -> None:
        """Bounded-exponential-backoff reconnect: fresh TCP connection plus
        a MSG_RESUME handshake carrying the last seq whose response fully
        arrived. The caller then re-sends its in-flight frame under the
        original seq and the coordinator answers from its replay cache.
        Raises a fully-attributed ShutdownError once attempts run out.

        With HOROVOD_STANDBY_COORD set, attempts after the first also probe
        the KV store for a promoted standby's address (addr.{gen}.f{n}) and
        redirect there — that is the entire worker half of coordinator
        failover; everything downstream is the ordinary RESUME + replay +
        RANKS_CHANGED machinery (docs/control-plane.md)."""
        last: Exception = why
        for attempt in range(1, self._reconnect_attempts + 1):
            delay = _backoff_schedule(self._rank, attempt,
                                      self._reconnect_backoff,
                                      self._reconnect_backoff_max,
                                      self._reconnect_jitter)
            if self._stop.wait(delay):
                raise ShutdownError("control plane shut down")
            if self._standby_enabled and attempt >= 2:
                self._probe_failover()
            try:
                sock = socket.create_connection((self._host, self._port),
                                                timeout=5)
                sock.settimeout(0.5)
                if self._faults is not None:
                    sock = self._faults.wrap(sock)
                    # after a followed failover the peer is a promoted
                    # standby, not rank 0 — leave it unattributed so the
                    # partition rule cannot misfire on the new pair
                    sock.set_peer(0 if self._fo == 0 else None)
                wire.send_frame(sock, self._secret, MSG_RESUME, 0,
                                self._rank,
                                wire.encode_resume(self._last_acked),
                                fence=self._guard.epoch)
            except (ConnectionError, OSError) as exc:
                last = exc
                continue
            with self._send_lock:
                old, self._sock = self._sock, sock
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass
            instruments.control_reconnects().inc()
            _blackbox.record(_blackbox.K_RECONNECT, "rank_%d" % self._rank,
                             "reconnected after %s (attempt %d)"
                             % (why, attempt), rank=self._rank)
            logger.warning(
                "control plane: reconnected to coordinator %s after %s "
                "(attempt %d, replaying seq %s, last acked seq %s)",
                self._addr, why, attempt, seq, self._last_acked)
            return
        raise ShutdownError(
            f"control-plane connection lost (coordinator {self._addr}, "
            f"rank {self._rank}, last sent seq {seq}, last acked seq "
            f"{self._last_acked}, {self._reconnect_attempts} reconnect "
            f"attempts failed, last error "
            f"errno={getattr(last, 'errno', None)}: {last!r})")

    # ------------------------------------- survivable control plane helpers
    def _start_subcoord(self, gen: int, up_host: str, up_port: int,
                        advertise: str) -> None:
        """Bring up every aggregator this host leader owns in the N-tier
        tree and publish their addresses (docs/control-plane.md). With
        HOROVOD_HIERARCHY_TIERS=1 (the default) that is exactly the old
        single host tier: one sub-coordinator under addr.{gen}.h{group}
        speaking legacy MSG_BATCH straight to rank 0. With deeper trees the
        leader of host group g owns the tier-t aggregator with index
        g // fanout^(t-1) whenever that divides evenly, brought up top tier
        first so each lower tier can resolve its parent's published
        address; the host tier then dials addr.{gen}.t2.{g // fanout}.
        The leader of the FIRST child under each mid-tier parent (child
        index ≡ 1 mod fanout) also runs that parent's warm TierStandby."""
        from .hierarchy import (SubCoordinator, TierStandby,
                                parse_tier_config)

        group = int(os.environ.get("HVD_CROSS_RANK", "0") or "0")
        tiers, fanout = parse_tier_config()
        bind = "127.0.0.1" if advertise == "127.0.0.1" else "0.0.0.0"
        instruments.coord_tier_depth().set(tiers)
        for t in range(tiers, 1, -1):
            span = fanout ** (t - 1)  # host groups per tier-t subtree
            if group % span != 0:
                continue
            agg = self._make_tier_agg(gen, t, group // span, up_host,
                                      up_port, tiers, fanout, bind)()
            _publish_key(f"addr.{gen}.t{t}.{group // span}",
                         f"{advertise}:{agg.port}", self._secret)
            self._tier_aggs.append(agg)
        for t in range(2, tiers + 1):
            cspan = fanout ** (t - 2)  # host groups per tier-(t-1) child
            if group % cspan != 0:
                continue
            child = group // cspan  # our child index under tier t
            if child % fanout != 1:
                continue
            sb = TierStandby(
                gen, t, child // fanout, self._secret,
                make_aggregator=self._make_tier_agg(
                    gen, t, child // fanout, up_host, up_port, tiers,
                    fanout, bind),
                advertise=advertise)
            sb.start()
            self._tier_standbys.append(sb)
        if tiers >= 2:
            ukey = f"addr.{gen}.t2.{group // fanout}"
            uaddr, _ = _resolve_key(ukey, 120.0)
            uhost, uport = uaddr.rsplit(":", 1)
            uport = int(uport)
            ufail = ukey
        else:
            uhost, uport = up_host, up_port
            ufail = f"addr.{gen}" if self._standby_enabled else None
        self._subcoord = SubCoordinator(
            uhost, uport, self._secret, leader_rank=self._rank, host=bind,
            tier=1, index=group, tiers=tiers, up_fail_base=ufail)
        _publish_key(f"addr.{gen}.h{group}",
                     f"{advertise}:{self._subcoord.port}", self._secret)

    def _make_tier_agg(self, gen: int, t: int, index: int, up_host: str,
                       up_port: int, tiers: int, fanout: int, bind: str):
        """Factory closure for the tier-t aggregator with ``index``; also
        what the tier's warm standby calls at promotion time to build the
        replacement (which re-resolves its parent, so promotion composes
        with upstream failovers)."""
        from .hierarchy import SubCoordinator

        def make():
            if t == tiers:
                uhost, uport = up_host, up_port
                ufail = f"addr.{gen}" if self._standby_enabled else None
            else:
                ukey = f"addr.{gen}.t{t + 1}.{index // fanout}"
                uaddr, _ = _resolve_key(ukey, 30.0)
                uhost, p = uaddr.rsplit(":", 1)
                uport, ufail = int(p), ukey
            return SubCoordinator(
                uhost, uport, self._secret, leader_rank=self._rank,
                host=bind, tier=t, index=index, tiers=tiers,
                up_fail_base=ufail)

        return make

    def _make_standby_state(self) -> "CoordState":
        c = self._state_ctor
        return CoordState(c["world"], c["threshold"], c["cache_capacity"],
                          c["stall_warning_s"], c["stall_shutdown_s"],
                          tuner=None, elastic=True)

    def _fence_primary(self, reason: str) -> None:
        """Lease renewal-thread callback on rank 0: the lease was lost
        (deposed) or unrenewable past the fence deadline — park the
        exchange NOW so no frame from this stale leader is ever obeyed.
        The server keeps answering with MSG_FENCED so late dials learn
        why (runtime/lease.py self-records the blackbox event)."""
        if self._state is not None:
            self._state.fence(reason)

    def _probe_failover(self) -> None:
        """A dead primary may have left a promoted standby behind: look for
        the next failover address with a short timeout and, if published,
        aim all further reconnect attempts (and direct dials) at it."""
        try:
            addr, secret = _resolve_key(
                f"addr.{self._gen}.f{self._fo + 1}", timeout=0.3)
        except Exception:
            return  # nothing promoted (yet); keep redialing the old address
        self._fo += 1
        from . import lease as _lease
        if _lease.lease_enabled():
            # the promoted standby bumped the fencing epoch when it took
            # the lease; learn it here so frames from the deposed primary
            # are rejected from the very first exchange with the new leader
            self._guard.observe(_lease.read_lease_epoch(self._gen))
        host, port = addr.rsplit(":", 1)
        if not self._hier:
            # hierarchical workers stay pinned to their LOCAL
            # sub-coordinator (which follows the failover itself); only the
            # direct rank-0 path below re-aims
            self._addr = addr
            self._host, self._port, self._secret = host, int(port), secret
        self._host0, self._port0, self._secret0 = host, int(port), secret
        with self._direct_lock:
            if self._direct_sock is not None:
                try:
                    self._direct_sock.close()
                except OSError:
                    pass
                self._direct_sock = None
        _blackbox.record(_blackbox.K_FAILOVER, "rank_%d" % self._rank,
                         "redialing promoted standby at %s (failover %d)"
                         % (addr, self._fo), rank=self._rank)
        logger.warning("control plane: rank %d following coordinator "
                       "failover %d to %s", self._rank, self._fo, addr)

    def _direct_request_reply(self, msg_type: int, resp_type: int,
                              frame_seq: int, payload: bytes) -> bytes:
        """Hierarchical mode: DATA/CLOCK exchanges carry bulk payloads and
        per-rank state, so they bypass the sub-coordinator on a lazily
        dialed direct connection to rank 0 instead of funneling through
        one host process. One redial on connection loss (more when a warm
        standby may be promoting, with failover-key probing from the
        second retry); the coordinator's replay caches make the re-send
        idempotent."""
        last: Optional[Exception] = None
        attempts = (self._reconnect_attempts if self._standby_enabled
                    else 2)
        for attempt in range(attempts):
            if attempt and self._standby_enabled:
                if self._stop.wait(_backoff_schedule(
                        self._rank, attempt, self._reconnect_backoff,
                        self._reconnect_backoff_max,
                        self._reconnect_jitter)):
                    raise ShutdownError("control plane shut down")
                if attempt >= 2:
                    self._probe_failover()
            try:
                with self._direct_lock:
                    sock = self._direct_sock
                    if sock is None:
                        sock = socket.create_connection(
                            (self._host0, self._port0), timeout=5)
                        sock.settimeout(0.5)
                        wire.send_frame(sock, self._secret0, MSG_HELLO, 0,
                                        self._rank)
                        self._direct_sock = sock
                with self._direct_send_lock:
                    wire.send_frame(sock, self._secret0, msg_type,
                                    frame_seq, self._rank, payload,
                                    fence=self._guard.epoch)
                while True:
                    mt, rseq, _, data = wire.recv_frame(
                        sock, self._secret0, self._stop, guard=self._guard)
                    if mt == MSG_FENCED:
                        raise ConnectionError(
                            "coordinator at %s:%s is fenced (%s)" % (
                                self._host0, self._port0,
                                data.decode("utf-8", "replace")
                                or "lost leadership lease"))
                    if mt == resp_type and rseq == frame_seq:
                        return data
            except (ConnectionError, OSError) as exc:
                last = exc
                with self._direct_lock:
                    if self._direct_sock is not None:
                        try:
                            self._direct_sock.close()
                        except OSError:
                            pass
                        self._direct_sock = None
                if self._stop.is_set():
                    raise ShutdownError("control plane shut down")
        raise ConnectionError(
            f"direct control connection to rank 0 lost: {last!r}")

    def push_metrics(self) -> None:
        """Ship this rank's registry snapshot to the coordinator as a
        fire-and-forget MSG_METRICS frame (engine loop calls this every
        HOROVOD_METRICS_INTERVAL seconds). Rank 0's registry is directly
        visible to the endpoint, so it has nothing to ship."""
        if self._rank == 0 or self._sock is None:
            return
        from ..metrics import local_snapshot

        payload = wire.encode_metrics_report(
            self._rank, time.time(), local_snapshot())
        try:
            with self._send_lock:
                wire.send_frame(self._sock, self._secret, MSG_METRICS, 0,
                                self._rank, payload)
        except (ConnectionError, OSError):
            pass  # telemetry only; the control path will surface the loss

    def push_blackbox(self, doc_json: str) -> None:
        """Ship this rank's postmortem flight-recorder dump to rank 0 as a
        fire-and-forget MSG_BLACKBOX frame, so the bundle carries every
        reachable rank even when workers have no shared filesystem. Called
        once, from blackbox.dump(), before the BYE that tears the
        connection down. Rank 0 writes its dump locally."""
        if self._rank == 0 or self._sock is None:
            return
        payload = wire.encode_blackbox_dump(self._rank, time.time(),
                                            doc_json)
        try:
            with self._send_lock:
                wire.send_frame(self._sock, self._secret, MSG_BLACKBOX, 0,
                                self._rank, payload)
        except (ConnectionError, OSError):
            pass  # the local rank_N.json still exists; only shipping failed

    def send_ckpt_mark(self, step: int, epoch: int, index: int) -> None:
        """Stamp the checkpoint consistency epoch: fire-and-forget
        MSG_CKPT_MARK announcing this rank snapshotted its shard for
        ``step``. Rank 0 owns the state and stamps directly."""
        if self._rank == 0:
            if self._state is not None:
                self._state.ckpt_mark(0, step, epoch)
            return
        if self._sock is None:
            return
        payload = wire.encode_ckpt_mark(step, epoch, index)
        try:
            with self._send_lock:
                wire.send_frame(self._sock, self._secret, MSG_CKPT_MARK, 0,
                                self._rank, payload)
        except (ConnectionError, OSError):
            pass  # the DONE (or the next mark) will re-stamp

    def send_ckpt_done(self, step: int, epoch: int, index: int,
                       nbytes: int, crc: int) -> None:
        """Report this rank's shard file landed (fire-and-forget
        MSG_CKPT_DONE, sent from the writer thread). The bundle manifest
        finalizes on rank 0 once every member of the step reported."""
        if self._rank == 0:
            if self._state is not None:
                self._state.ckpt_done(0, step, epoch, index, nbytes, crc)
            return
        if self._sock is None:
            return
        payload = wire.encode_ckpt_done(step, epoch, index, nbytes, crc)
        try:
            with self._send_lock:
                wire.send_frame(self._sock, self._secret, MSG_CKPT_DONE, 0,
                                self._rank, payload)
        except (ConnectionError, OSError):
            pass  # an unfinalized bundle is pruned later; never fatal

    def push_traces(self) -> None:
        """Ship this rank's completed trace spans as a fire-and-forget
        MSG_TRACE frame (engine loop calls this every
        HOROVOD_TRACE_INTERVAL seconds). Rank 0 owns the merge store, so it
        drains locally instead of going over the wire."""
        from .. import tracing as _tracing

        tr = _tracing.active()
        if tr is None:
            return
        if self._rank == 0 or self._sock is None:
            _tracing.flush_local()
            return
        spans = tr.drain()
        if not spans:
            return
        payload = wire.encode_trace_batch(self._rank, spans)
        try:
            with self._send_lock:
                wire.send_frame(self._sock, self._secret, MSG_TRACE, 0,
                                self._rank, payload)
        except (ConnectionError, OSError):
            pass  # telemetry only; the drained batch is lost, not the job

    def _sync_trace_clock(self, rounds: int = 5) -> None:
        """NTP-style offset handshake against rank 0 (docs/tracing.md):
        each probe carries the local trace timestamp, the reply carries
        rank 0's; the minimum-RTT sample wins. The reply also distributes
        the job's globally-unique trace id."""
        from .. import tracing as _tracing

        # sub-coordinators do not answer CLOCK: in hierarchical mode probe
        # rank 0 directly so offsets measure the rank-0 wire, not the relay
        rr = (self._direct_request_reply if self._hier
              else self._request_reply)

        def probe(t_local_us):
            data = rr(MSG_CLOCK, MSG_CLOCK_RESP, 0,
                      wire.encode_clock_probe(t_local_us))
            server_us, tid = wire.decode_clock_reply(data)
            if tid:
                _tracing.set_trace_id(tid)
            return server_us

        off = _tracing.clock.sync_offset(probe, rounds=rounds)
        logger.info("trace clock: rank %d offset to rank 0 is %d us",
                    self._rank, off)

    # -------------------------------------------------------------- elastic
    def commit(self) -> None:
        """Mark a commit boundary: REQ_COMMIT rides the next request frame.
        Joiners waiting at the coordinator are admitted once every current
        member has committed (docs/elastic.md)."""
        with self._lock:
            self._commit_pending = True

    def resume(self) -> None:
        """Acknowledge a membership reset: re-enable submits after
        ElasticState.sync() realigned the training state."""
        with self._lock:
            self._ranks_changed_reason = None

    def members(self) -> List[int]:
        with self._lock:
            return list(self._members)

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def _apply_ranks_changed(self, epoch: int, members: List[int],
                             reason: str):
        """Adopt the coordinator's new membership and raise. The sig cache
        dies with the coordinator's id table; the tick counter realigns so
        survivors' next exchanges share a sequence number regardless of how
        far each had advanced; every later submit fails with
        SUBMIT_RANKS_CHANGED until resume()."""
        instruments.elastic_epoch().set(epoch)
        with self._lock:
            self._epoch = epoch
            self._members = sorted(members)
            self._seq = epoch * EPOCH_SEQ_BASE
            self._dseq = 0
            self._sig_cache.clear()
            self._inflight.clear()
            self._outbox.clear()
            self._ranks_changed_reason = reason or "cluster membership changed"
        self._timeline.epoch_marker(epoch)
        from .. import tracing as _tracing
        tr = _tracing.active()
        if tr is not None:
            # the merged trace shows exactly which spans straddled the reset
            tr.add_mark(self._rank, f"EPOCH_{epoch}",
                        _tracing.clock.trace_us())
        msg = (f"membership epoch {epoch}: members {self._members}"
               + (f" ({reason})" if reason else ""))
        if "lost" in (reason or ""):
            raise WorkerLostError(msg)
        raise RanksChangedError(msg)

    def data_exchange(self, op: int, root: int, array):
        """Elastic host-wire collective: ship this rank's buffer through the
        coordinator, get back the combined buffer and the participant count.
        Blocking; engine-thread only (strict request/reply on the one
        control-plane socket). Raises RanksChangedError/WorkerLostError when
        membership changed under the exchange."""
        import numpy as np

        arr = np.ascontiguousarray(array)
        with self._lock:
            epoch = self._epoch
            dseq = self._dseq
            self._dseq += 1
        payload = wire.encode_data_request(epoch, dseq, op, root,
                                           str(arr.dtype), arr.shape,
                                           arr.tobytes())
        frame_seq = dseq & 0xFFFFFFFF
        try:
            if self._rank == 0:
                assert self._state is not None
                data = self._state.data_exchange(0, payload)
            else:
                if self._faults is not None:
                    self._faults.fire("exchange")
                if self._hier:
                    data = self._direct_request_reply(
                        MSG_DATA, MSG_DATA_RESP, frame_seq, payload)
                else:
                    data = self._request_reply(MSG_DATA, MSG_DATA_RESP,
                                               frame_seq, payload)
        except (ConnectionError, OSError) as exc:
            raise ShutdownError(
                f"control-plane connection lost during data exchange "
                f"(coordinator {self._addr}, rank {self._rank}, epoch "
                f"{epoch}, dseq {dseq}, "
                f"errno={getattr(exc, 'errno', None)}: {exc!r})")
        (status, repoch, nparticipants, rmembers,
         raw) = wire.decode_data_result(data)
        if status == wire.DATA_RANKS_CHANGED:
            self._apply_ranks_changed(
                repoch, rmembers or [],
                raw.decode("utf-8", "replace") or "membership changed "
                "during collective")
        if status == wire.DATA_ERROR:
            raise ShutdownError(raw.decode("utf-8", "replace")
                                or "elastic data exchange failed")
        out = np.frombuffer(raw, dtype=arr.dtype).reshape(arr.shape)
        # members rides the DATA_OK reply only on partial rounds (straggler
        # exclusion): the actual contributor list, read by ElasticExecutor
        # for EF residual accounting. None ⇒ everyone contributed.
        self.last_data_contributors = list(rmembers) if rmembers else None
        return out.copy(), nparticipants

    def interrupt(self) -> None:
        """Unblock a tick in flight (called from the user thread on
        shutdown)."""
        if self._standby_coord is not None:
            # an intentionally-stopping rank 1 must never read the ensuing
            # connection teardown as a dead coordinator and promote itself
            self._standby_coord.stop()
        self._send_bye()
        self._stop.set()

    def _send_bye(self) -> None:
        with self._send_lock:
            if self._bye_sent:
                return
            self._bye_sent = True
            if self._rank == 0 and self._state is not None:
                self._state.set_bye()
            elif self._sock is not None:
                try:
                    wire.send_frame(self._sock, self._secret, MSG_BYE, 0,
                                    self._rank)
                except OSError:
                    pass

    def shutdown(self) -> List[int]:
        # final span drain must beat the BYE: after it the socket dies and
        # anything still in the ring would never reach rank 0's merged trace
        try:
            self.push_traces()
        except Exception:
            pass
        if self._standby_coord is not None:
            self._standby_coord.stop()
        if self._lease is not None:
            self._lease.stop()
        self._send_bye()
        self._stop.set()
        with self._lock:
            orphans = [r.handle for r in self._inflight.values()]
            if self._join_handle is not None:
                orphans.append(self._join_handle)
            self._inflight.clear()
            self._outbox.clear()
            self._join_handle = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._direct_lock:
            if self._direct_sock is not None:
                try:
                    self._direct_sock.close()
                except OSError:
                    pass
                self._direct_sock = None
        if self._subcoord is not None:
            self._subcoord.stop()
        for sb in self._tier_standbys:
            sb.stop()
        for agg in self._tier_aggs:
            agg.stop()
        if self._server is not None:
            if self._state is not None and self._state.fenced:
                # a fenced coordinator keeps its listener up for the rest of
                # the process lifetime: peers partitioned away from it must,
                # after the heal, receive an explicit FENCED stamped with the
                # deposed epoch — a refused connection is indistinguishable
                # from a crash and would leave them probing forever. The
                # accept loop is a daemon thread; the port dies with the
                # process, and a fence is terminal for this generation so no
                # shutdown()+init() cycle ever reuses this server.
                logger.info("coordinator: fenced — leaving the FENCED "
                            "responder up until process exit")
            else:
                # set_bye already ran (via _send_bye), so any rank still
                # blocked in an exchange has been released with a shutdown
                # response; stragglers that connect later see a reset and
                # treat it as shutdown. Stopping here frees the port and
                # accept thread so shutdown()+init() cycles don't leak.
                self._server.stop()
        self._timeline.close()
        if self._state is not None and self._state.tuner is not None:
            self._state.tuner.close()
            self._state.tuner = None
        return orphans

    # ---- timeline / autotune / stats
    def timeline_op_start(self, tensor: str, op: str) -> None:
        self._timeline.op_start(tensor, op)

    def timeline_activity(self, tensor: str, activity: str) -> None:
        self._timeline.activity(tensor, activity)

    def timeline_op_end(self, tensor: str) -> None:
        self._timeline.op_end(tensor)

    def timeline_cycle(self) -> None:
        self._timeline.cycle_tick()

    def timeline_cache(self, hits: int, misses: int) -> None:
        self._timeline.cache_counter(hits, misses)

    def report_score(self, nbytes: int, seconds: float) -> bool:
        """Buffer a local throughput sample for the next request frame; the
        GP/EI runs at the coordinator and tuned params return in the
        ResponseList (reference: the controller broadcasts parameter-manager
        updates with the response plan). Always returns False — the engine
        picks up tuned cycle time by re-reading cycle_time_ms() after each
        coordinated tick, not through this return value."""
        if not self._autotune:
            return False
        with self._lock:
            if self._score_bytes == 0:
                # open the wall-clock window at (roughly) this op's start
                self._score_epoch = time.monotonic() - seconds
            self._score_bytes += int(nbytes)
            self._score_busy += float(seconds)
        return False

    def fusion_threshold(self) -> int:
        return self._threshold

    def cycle_time_ms(self) -> float:
        return self._cycle_ms

    def cache_stats(self) -> Tuple[int, int]:
        if self._state is not None:
            return self._state.cache_stats()
        return (self._hits, self._misses)


def _backoff_schedule(rank: int, attempt: int, base: float, cap: float,
                      jitter: float) -> float:
    """Reconnect delay before ``attempt`` (1-based): bounded exponential
    backoff, optionally spread per-rank by ``HOROVOD_RECONNECT_JITTER`` so
    a mass reconnect (every worker losing the coordinator at once) does
    not land on the new coordinator as one synchronized thundering herd.
    The jitter term is deterministic per (rank, attempt), keeping chaos
    tests reproducible: delay in [backoff, backoff * (1 + jitter)]."""
    delay = min(base * (2 ** (attempt - 1)), cap)
    if jitter > 0:
        u = ((rank * 2654435761 + attempt * 97) % 1024) / 1024.0
        delay *= 1.0 + jitter * u
    return delay


def _advertise_host() -> str:
    kv = os.environ.get("HVD_KV_ADDR", "")
    if kv.startswith("127.") or kv.startswith("localhost"):
        return "127.0.0.1"
    from ..run.rendezvous import local_ip

    return local_ip()
