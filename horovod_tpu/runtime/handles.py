"""Async operation handles.

Reference parity: `horovod/torch/handle_manager.{h,cc}` — integer handles
allocated at enqueue; completion marks status + result; ``synchronize`` blocks,
``poll`` is non-blocking (`torch/mpi_ops.py:460-509`).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from ..exceptions import HorovodInternalError


class _HandleEntry:
    __slots__ = ("event", "ok", "result", "error", "error_cls")

    def __init__(self):
        self.event = threading.Event()
        self.ok = False
        self.result = None
        self.error: Optional[str] = None
        self.error_cls = HorovodInternalError


class HandleManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._entries: Dict[int, _HandleEntry] = {}

    def allocate(self) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._entries[h] = _HandleEntry()
            return h

    def mark_done(self, handle: int, ok: bool, result: Any = None,
                  error: Optional[str] = None,
                  error_cls=HorovodInternalError) -> None:
        with self._lock:
            e = self._entries.get(handle)
        if e is None:
            return
        e.ok = ok
        e.result = result
        e.error = error
        e.error_cls = error_cls
        e.event.set()

    def poll(self, handle: int) -> bool:
        with self._lock:
            e = self._entries.get(handle)
        return e is not None and e.event.is_set()

    def synchronize(self, handle: int, timeout: Optional[float] = None) -> Any:
        with self._lock:
            e = self._entries.get(handle)
        if e is None:
            raise HorovodInternalError(f"unknown handle {handle}")
        if not e.event.wait(timeout):
            raise HorovodInternalError(f"timeout waiting for handle {handle}")
        with self._lock:
            self._entries.pop(handle, None)
        if not e.ok:
            raise e.error_cls(e.error or "collective failed")
        return e.result
