"""Background collective engine: queue → negotiation → fusion → execute.

Reference parity: the background-thread engine in `horovod/common/operations.cc`
(`BackgroundThreadLoop` :328, `RunLoopOnce` :531, `PerformOperation` :227).

Architecture (mirrors the reference's split of C++ engine + framework
callbacks): the **control plane** — tensor table, readiness negotiation,
cross-rank validation, fusion planning, response cache, stall inspection,
timeline, autotune — lives in the native C++ core
(`horovod_tpu/_core/`, loaded via ctypes; pure-Python fallback in
`pycontroller.py`). The **data plane** is XLA: this engine thread decodes the
controller's wire-encoded responses and hands each fused response to the
executor, which runs ONE compiled collective over the device mesh. Completion
fires per-tensor callbacks/handles, preserving horovod's async op-by-op
semantics.

Env knobs (parity with `common.h:61-87` / `operations.cc:388-485`):
  HOROVOD_FUSION_THRESHOLD (bytes, default 64 MB, operations.cc:404)
  HOROVOD_CYCLE_TIME       (ms,   default 5,     operations.cc:412)
  HOROVOD_CACHE_CAPACITY   (default 1024)
  HOROVOD_STALL_CHECK_TIME_SECONDS (default 60,  stall_inspector.h:75)
  HOROVOD_STALL_SHUTDOWN_TIME_SECONDS (default 0 = never, stall_inspector.h:80)
  HOROVOD_STALL_CHECK_DISABLE (1 = never warn/shutdown, env_parser.cc:120)
  HOROVOD_AUTOTUNE_WARMUP_SAMPLES / _STEPS_PER_SAMPLE /
  _BAYES_OPT_MAX_SAMPLES / _GAUSSIAN_PROCESS_NOISE
                           (tuner cadence knobs, parameter_manager.cc:42-59)
  HOROVOD_TIMELINE         (path for Chrome-trace output)
  HOROVOD_AUTOTUNE         (1 = GP/EI tuning of fusion threshold+cycle time)
  HVD_TPU_NATIVE           (0 = force the pure-Python controller)
  HOROVOD_COMPRESSION      (none/fp16/bf16/int8/int8-dcn — job-wide default
                            wire compression; int8* negotiate the quantized
                            collective program, docs/compression.md)
  HOROVOD_INT8_BLOCK       (quantization block length, default 256)
  HOROVOD_COMPRESSION_MIN_SIZE (elements; buckets below it skip
                            quantization, default 1024)
  HOROVOD_BUCKET_MB        (backward-pass bucket overlap: gradient pytrees
                            partition into buckets of this many MiB in
                            reverse-production order, each enqueued as its
                            own non-fusable collective so early buckets hit
                            the wire while the tail still computes;
                            0/unset = per-leaf path unchanged,
                            docs/overlap.md)
  HOROVOD_PACKED_WIRE      (1 = single-buffer int8 wire: payload and scale
                            bytes packed per block into ONE all_to_all +
                            ONE all_gather via the fused quantize+pack
                            kernel; default 0 keeps the two-collective
                            PR-1 wire, docs/overlap.md)

Autotune and compression: quantized allreduces are scored by the bytes the
wire actually moved (integer payload + f32 scales, Executor.last_wire_bytes),
not the fp32 bucket size, so the tuner's fusion threshold learns the
compressed wire's economics. Static compression modes are never tuned —
each is negotiated once through the coordinated controller's response
metadata (Response.compression) so all ranks compile identical programs;
per-sample flapping would recompile every bucket. The adaptive wire
(HOROVOD_COMPRESSION=adaptive) adds one tuned axis on top: the coordinator's
BitwidthTuner (ops/adaptive.py) searches bitwidth CAPS over the same
wire-true scores and broadcasts the winner as the third tuned field, while
the per-bucket int4/int8/bf16 choice under that cap still flows through
negotiated Response.compression — decisions change at observation-interval
boundaries, not per sample, so recompiles stay rare and every rank compiles
the same program for the same bucket.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from ..exceptions import (CollectiveTimeoutError, DuplicateNameError,
                          HorovodInternalError, RanksChangedError,
                          ShutdownError)
from ..goodput import ledger as _goodput
from ..metrics import instruments
from .. import blackbox as _blackbox
from .. import faultinject
from .. import tracing as _tracing
from ..utils.env import env_float as _env_float, env_on as _env_on
from .executor import Executor
from .handles import HandleManager
from .messages import RequestType, Response, ResponseType, TensorTableEntry

DEFAULT_FUSION_BYTES = 64 * 1024 * 1024
DEFAULT_CYCLE_MS = 5.0

logger = logging.getLogger("horovod_tpu")


def _timeline_path(mode: str, self_rank: int) -> "Optional[str]":
    """Rank 0 writes HOROVOD_TIMELINE verbatim; in multiprocess mode every
    other rank writes its LOCAL activity spans to ``<path>.rank<N>``
    (reference ``--output-filename``-style suffixing) — a hung worker keeps
    local observability instead of being trace-blind."""
    path = os.environ.get("HOROVOD_TIMELINE")
    if not path:
        return None
    if mode != "multiprocess" or self_rank == 0:
        return path
    return f"{path}.rank{self_rank}"


def _stall_knobs():
    """(warning_s, shutdown_s) with HOROVOD_STALL_CHECK_DISABLE folded in:
    disabling the check (`env_parser.cc:120`) means neither warning nor
    forced shutdown ever fires, regardless of the time knobs."""
    if _env_on("HOROVOD_STALL_CHECK_DISABLE"):
        return float("inf"), 0.0
    return (_env_float("HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0),
            _env_float("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0))


def _make_controller(world: int, mode: str, self_rank: int = 0):
    fusion_threshold = int(_env_float("HOROVOD_FUSION_THRESHOLD",
                                      DEFAULT_FUSION_BYTES))
    cycle_ms = _env_float("HOROVOD_CYCLE_TIME", DEFAULT_CYCLE_MS)
    stall_warning_s, stall_shutdown_s = _stall_knobs()
    if mode == "multiprocess" and world > 1:
        # cross-process control plane: negotiation/validation/fusion/
        # allgather/join coordinated at rank 0 (controller.cc:55-336 +
        # mpi_controller.cc:107-161 parity). The decision to use it must be
        # IDENTICAL on every rank — has_address_channel() depends only on
        # launcher env / jax.distributed state, which are uniform across the
        # job — and once taken, a setup failure is fatal: a per-rank silent
        # fallback would leave ranks on different control planes and hang.
        from .coordinator import CoordController, has_address_channel

        if has_address_channel():
            ctrl = CoordController(
                world=world,
                fusion_threshold=fusion_threshold,
                stall_warning_s=stall_warning_s,
                stall_shutdown_s=stall_shutdown_s,
                cache_capacity=int(_env_float("HOROVOD_CACHE_CAPACITY", 1024)),
                fusion_enabled=True,
                timeline_path=_timeline_path(mode, self_rank),
                autotune=_env_on("HOROVOD_AUTOTUNE"),
                cycle_time_ms=cycle_ms,
                self_rank=self_rank,
            )
            return ctrl, False
        logger.warning(
            "no coordinator address channel (no HVD_KV_ADDR and no "
            "jax.distributed KV); using SPMD program-order agreement "
            "(fusion/allgather/join disabled)")
    kwargs = dict(
        world=world,
        fusion_threshold=fusion_threshold,
        stall_warning_s=stall_warning_s,
        stall_shutdown_s=stall_shutdown_s,
        cache_capacity=int(_env_float("HOROVOD_CACHE_CAPACITY", 1024)),
        # multiprocess fusion requires the cross-process control plane:
        # bucket contents must not depend on per-process tick timing
        fusion_enabled=(mode != "multiprocess"),
        # rank 0 writes the shared path; multiprocess workers write local
        # activity to a .rank<N>-suffixed file (never the shared path —
        # concurrent writers would corrupt the JSON, operations.cc:389-396)
        timeline_path=_timeline_path(mode, self_rank),
        autotune=_env_on("HOROVOD_AUTOTUNE"),
        cycle_time_ms=cycle_ms,
        # multiprocess: only the local rank submits to this process's table;
        # readiness must not wait on remote ranks (they negotiate in their own
        # process; agreement is SPMD program order)
        local_only=(mode == "multiprocess"),
        self_rank=self_rank,
    )
    try:
        from .native import NativeController

        return NativeController(**kwargs), True
    except Exception as exc:  # toolchain-less host or HVD_TPU_NATIVE=0
        if os.environ.get("HVD_TPU_NATIVE", "1") not in ("0", "false"):
            logger.warning("native core unavailable (%s); using Python "
                           "controller", exc)
        from .pycontroller import PyController

        return PyController(**kwargs), False


class Engine:
    """One engine per process; owns the negotiation thread and executor."""

    def __init__(self, state):
        self._state = state
        self._world = state.size
        self._mode = state.mode
        self.handles = HandleManager()
        self.controller, self.native = _make_controller(
            state.size, state.mode, state.rank0)
        if getattr(state, "elastic", False):
            # elastic jobs have no cross-process XLA collectives
            # (jax.distributed is skipped so workers can die/join); the data
            # plane rides the coordinator's TCP channel instead
            from ..elastic.executor import ElasticExecutor

            self._executor = ElasticExecutor(state, self.controller)
        else:
            self._executor = Executor(state)

        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        # controller handle -> (entry, user_handle)
        self._pending: Dict[int, TensorTableEntry] = {}
        self._join_waiters: Dict[int, int] = {}  # ctrl handle -> user handle
        self._shutdown = False
        self._thread: Optional[threading.Thread] = None
        self.cycle_time_s = self.controller.cycle_time_ms() / 1e3
        # shape signatures already executed once: first executions include
        # XLA compile time and must not be scored for autotune
        self._scored_sigs: set = set()
        self._last_cache_stats = (0, 0)
        # wire/exact byte accumulators behind the quantization-ratio gauge
        self._wire_acc = 0
        self._exact_acc = 0
        # per-rank snapshot shipping cadence (docs/metrics.md); coordinated
        # controllers expose push_metrics(), everything else shares one
        # process registry and has nothing to ship
        self._metrics_interval = _env_float("HOROVOD_METRICS_INTERVAL", 5.0)
        self._metrics_next_push = time.monotonic() + self._metrics_interval
        # distributed tracing (docs/tracing.md): active only when
        # HOROVOD_TRACE names a merged-output path; otherwise active() stays
        # None and every instrumentation site is one attribute read
        _tracing.maybe_activate()
        self._trace_interval = _env_float("HOROVOD_TRACE_INTERVAL", 2.0)
        self._trace_next_push = time.monotonic() + self._trace_interval
        # flight recorder (docs/observability.md): same no-op discipline as
        # tracing — active() stays None unless HOROVOD_BLACKBOX is set
        _blackbox.maybe_activate()
        _blackbox.set_identity(state.rank0, state.size)
        _blackbox.set_shipper(getattr(self.controller, "push_blackbox",
                                      None))
        # wire/exact totals at the last flight-recorder metric delta
        self._bb_wire_prev = 0
        self._bb_exact_prev = 0
        # pre-touch the catalog's unlabeled series (inc(0) materializes the
        # child) so /metrics renders them at 0 before the first negotiation
        instruments.response_cache_hits().inc(0)
        instruments.response_cache_misses().inc(0)
        instruments.engine_ticks().inc(0)
        instruments.control_reconnects().inc(0)
        instruments.heartbeat_misses().inc(0)
        instruments.frames_rejected().inc(0)
        instruments.grad_nonfinite().inc(0)
        instruments.steps_skipped().inc(0)
        instruments.param_desync().inc(0)
        instruments.integrity_heals().inc(0)
        instruments.collective_timeouts().inc(0)
        instruments.trace_dropped_events().inc(0)
        instruments.partial_collectives().inc(0)
        instruments.straggler_promotions().inc(0)
        instruments.excluded_rank().set(-1)
        epoch_fn = getattr(self.controller, "epoch", None)
        instruments.elastic_epoch().set(
            max(0, epoch_fn()) if callable(epoch_fn) else 0)
        # per-rank data-plane fault point (slow@rank / flaky_slow@rank):
        # fires once per engine tick, modelling a chronically slow worker
        self._faults = faultinject.for_rank(state.rank0)
        # goodput ledger (docs/goodput.md): wall-clock attribution starts
        # at engine construction; liveness stamps let scrapers tell a
        # wedged-but-listening rank from a healthy one
        _goodput.attach(state.rank0)
        instruments.up().set(1.0)
        instruments.snapshot_unix_seconds().set(time.time())

    # ------------------------------------------------------------------ API
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="hvd_tpu_engine", daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            self._wake.notify_all()
        # a coordinated controller may be blocked mid-exchange; unblock it
        interrupt = getattr(self.controller, "interrupt", None)
        if interrupt is not None:
            interrupt()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def enqueue(self, entry: TensorTableEntry) -> int:
        """Add a named tensor; returns an async user handle.

        Mirrors EnqueueTensorAllreduce/-Allgather/-Broadcast
        (`operations.cc:783-934`); duplicate detection in the controller
        (DUPLICATE_NAME_ERROR `common.h:160`)."""
        user = self.handles.allocate()
        entry.handle = user
        fail = None
        with self._lock:
            if self._shutdown:
                fail = (ShutdownError, "Horovod has been shut down.")
            else:
                ch = self.controller.submit(entry)
                if ch == self.controller.SUBMIT_DUPLICATE:
                    fail = (DuplicateNameError,
                            f"Duplicate tensor name {entry.tensor_name!r}: "
                            f"a collective with this name from rank "
                            f"{entry.rank} is already pending.")
                elif ch == self.controller.SUBMIT_SHUTDOWN:
                    fail = (ShutdownError, "Horovod has been shut down.")
                elif ch == getattr(self.controller,
                                   "SUBMIT_RANKS_CHANGED", None):
                    fail = (RanksChangedError,
                            "cluster membership changed; restore committed "
                            "state and sync() before submitting new "
                            "collectives (docs/elastic.md)")
                else:
                    self._pending[ch] = entry
                    self._wake.notify_all()
        if fail is None:
            tr = _tracing.active()
            if tr is not None:
                tr.begin_collective(
                    entry.rank, entry.tensor_name, entry.request_type.name,
                    int(entry.array.size) * entry.array.dtype.itemsize,
                    _tracing.clock.trace_us())
            bb = _blackbox.active()
            if bb is not None:
                bb.record(_blackbox.K_COLLECTIVE, entry.tensor_name,
                          "enqueue %s" % entry.request_type.name, entry.rank)
        if fail is not None:
            # the completion contract covers submit-time failures too, and
            # callbacks must never run under the engine lock (they may call
            # back into the engine)
            cls, msg = fail
            self._fire_callback(entry, False, msg)
            self.handles.mark_done(user, False, error=msg, error_cls=cls)
        return user

    def join(self, rank: int) -> int:
        """Rank signals it has no more data (JoinOp, `operations.cc:908-934`)."""
        user = self.handles.allocate()
        with self._lock:
            if self._shutdown:
                self.handles.mark_done(user, False,
                                       error="Horovod has been shut down.",
                                       error_cls=ShutdownError)
                return user
            ch = self.controller.join(rank)
            # repeated join from the same rank reuses the controller handle;
            # every caller's user handle must release with the barrier
            self._join_waiters.setdefault(ch, []).append(user)
            self._wake.notify_all()
        return user

    def report_score(self, nbytes: int, seconds: float) -> None:
        changed = self.controller.report_score(nbytes, seconds)
        if changed:
            self.cycle_time_s = self.controller.cycle_time_ms() / 1e3
        # in-process tuner: log while it still explores (the coordinated
        # controller's samples are logged where they are aggregated and
        # scored — the rank-0 coordinator)
        active = getattr(self.controller, "autotune_active", None)
        if active is not None and (changed or active()):
            from ..utils.autotune_log import log_sample

            path = os.environ.get("HOROVOD_AUTOTUNE_LOG")
            if path and self._mode == "multiprocess" and self._state.rank0:
                # fallback (uncoordinated) multiprocess: every process has
                # its own tuner; same per-rank suffixing as the timeline
                path = f"{path}.rank{self._state.rank0}"
            log_sample(path, nbytes, seconds,
                       self.controller.fusion_threshold(),
                       self.controller.cycle_time_ms())

    # ----------------------------------------------------------------- loop
    def _loop(self) -> None:
        while True:
            try:
                with self._lock:
                    if (not self._shutdown and not self._pending
                            and not self._join_waiters):
                        self._wake.wait(timeout=self.cycle_time_s)
                    drained = (self._drain_locked() if self._shutdown
                               else None)
                if drained is not None:
                    self._finish_drain(*drained)
                    return
                if self._faults is not None:
                    # slow@rank / flaky_slow@rank: a chronically slow worker
                    # is modelled as dead time in its engine loop — the spot
                    # a real straggler loses its time (input pipeline, GC,
                    # noisy neighbour), upstream of the control-plane tick
                    self._faults.fire("rank")
                tick = self.controller.tick()
                instruments.engine_ticks().inc()
                now = time.monotonic()
                if now >= self._metrics_next_push:
                    self._metrics_next_push = now + self._metrics_interval
                    # flush the goodput ledger and restamp liveness BEFORE
                    # the push so the shipped snapshot carries attribution
                    # current to this tick
                    led = _goodput.active()
                    if led is not None:
                        led.flush()
                    instruments.up().set(1.0)
                    instruments.snapshot_unix_seconds().set(time.time())
                    push = getattr(self.controller, "push_metrics", None)
                    if push is not None:
                        push()
                    bb = _blackbox.active()
                    if bb is not None:
                        # the ring keeps the last K metric deltas so the
                        # dump shows throughput right up to the death
                        bb.record(
                            _blackbox.K_METRICS, "delta",
                            "wire_bytes+=%d exact_bytes+=%d"
                            % (self._wire_acc - self._bb_wire_prev,
                               self._exact_acc - self._bb_exact_prev))
                        self._bb_wire_prev = self._wire_acc
                        self._bb_exact_prev = self._exact_acc
                if (_tracing.active() is not None
                        and now >= self._trace_next_push):
                    self._trace_next_push = now + self._trace_interval
                    self._flush_traces()
                if getattr(self.controller, "coordinated", False):
                    # coordinated autotune delivers tuned cycle time inside
                    # the tick's ResponseList; pick it up even on idle ticks
                    self.cycle_time_s = self.controller.cycle_time_ms() / 1e3
                if tick is None:
                    time.sleep(self.cycle_time_s / 5)
                    continue
                (responses, handle_pairs, join_released, last_joined,
                 stall_warnings, stall_shutdown) = tick
                for name in stall_warnings:
                    # coordinated warnings arrive pre-formatted as
                    # "tensor (waiting on ranks [...] for Ns)"; split so the
                    # event names the tensor and the detail keeps the ranks
                    tensor, _, rest = name.partition(" (")
                    _blackbox.record(_blackbox.K_STALL, tensor,
                                     rest.rstrip(")")
                                     or "stalled past the warning threshold")
                    logger.warning(
                        "One or more tensors were submitted to be reduced/"
                        "gathered/broadcasted by subset of ranks and are "
                        "waiting for remainder of ranks for more than %ss. "
                        "Stalled op: %s",
                        os.environ.get("HOROVOD_STALL_CHECK_TIME_SECONDS",
                                       "60"), name)
                if responses:
                    self.controller.timeline_cycle()
                    hits, misses = self.controller.cache_stats()
                    if (hits, misses) != self._last_cache_stats:
                        # delta-based: native/pycontroller cache counters are
                        # cumulative totals; the coordinated path already
                        # counts at the negotiation site (rank 0), and its
                        # worker-side cache_stats mirror the local sig cache
                        dh = hits - self._last_cache_stats[0]
                        dm = misses - self._last_cache_stats[1]
                        if not getattr(self.controller, "coordinated", False):
                            if dh > 0:
                                instruments.response_cache_hits().inc(dh)
                            if dm > 0:
                                instruments.response_cache_misses().inc(dm)
                        self._last_cache_stats = (hits, misses)
                        self.controller.timeline_cache(hits, misses)
                for resp, pairs in zip(responses, handle_pairs):
                    self._perform(resp, pairs)
                if join_released:
                    with self._lock:
                        for ch in join_released:
                            for user in self._join_waiters.pop(ch, []):
                                self.handles.mark_done(user, True,
                                                       result=last_joined)
                if stall_shutdown:
                    raise RuntimeError(
                        "Stalled tensors exceeded "
                        "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS; aborting "
                        "(stall_inspector.h:80).")
            except RanksChangedError as exc:
                # elastic membership reset: fail everything in flight with
                # the reset error so user threads unblock into the elastic
                # recovery loop — then KEEP RUNNING; the engine survives the
                # epoch change and serves the re-synced training
                logger.warning("engine: %s; failing in-flight collectives "
                               "for elastic recovery", exc)
                # recoverable: record the reset, keep flying (no dump)
                _blackbox.record(_blackbox.K_EPOCH, type(exc).__name__,
                                 str(exc))
                with self._lock:
                    entries = list(self._pending.values())
                    self._pending.clear()
                    users = [u for us in self._join_waiters.values()
                             for u in us]
                    self._join_waiters.clear()
                for entry in entries:
                    self._fire_callback(entry, False, str(exc))
                    self.handles.mark_done(entry.handle, False,
                                           error=str(exc),
                                           error_cls=type(exc))
                for user in users:
                    self.handles.mark_done(user, False, error=str(exc),
                                           error_cls=type(exc))
                continue
            except ShutdownError as exc:
                # coordinated shutdown (a peer sent BYE / the coordinator
                # broadcast the shutdown flag): drain quietly — this is the
                # normal end-of-job path in multiprocess mode. A reasoned
                # shutdown (declared-dead worker, exhausted reconnects) is
                # abnormal: that one gets a flight-recorder dump.
                logger.info("engine: %s", exc)
                msg = str(exc)
                if msg not in ("coordinated shutdown",
                               "control plane shut down",
                               "Horovod has been shut down."):
                    _blackbox.record(_blackbox.K_ERROR, "ShutdownError", msg)
                    _blackbox.dump("shutdown: %s" % msg)
                with self._lock:
                    self._shutdown = True
                    drained = self._drain_locked()
                self._finish_drain(*drained)
                return
            except Exception as exc:
                logger.error("engine thread aborting: %s", exc)
                _blackbox.record(_blackbox.K_ERROR, type(exc).__name__,
                                 str(exc))
                _blackbox.dump("engine thread aborted: %s: %s"
                               % (type(exc).__name__, exc))
                with self._lock:
                    self._shutdown = True
                    drained = self._drain_locked()
                self._finish_drain(*drained)
                return

    def _flush_traces(self) -> None:
        """Ship this cadence's completed spans: coordinated controllers push
        an MSG_TRACE batch to rank 0; everything else shares the process-
        local merge store and drains straight into it."""
        push = getattr(self.controller, "push_traces", None)
        if push is not None:
            push()
        else:
            _tracing.flush_local()

    def _drain_locked(self):
        """Under the engine lock: stop the controller, snapshot and clear
        everything outstanding. Returns (entries, join_users) for
        `_finish_drain`, which must run with the lock RELEASED — user
        completion callbacks may call back into engine APIs."""
        if _tracing.active() is not None:
            try:
                self._flush_traces()
            except Exception:
                pass
        self.controller.shutdown()
        entries = list(self._pending.values())
        self._pending.clear()
        users = [u for us in self._join_waiters.values() for u in us]
        self._join_waiters.clear()
        return entries, users

    def _finish_drain(self, entries, users) -> None:
        """Fail everything outstanding with shutdown error
        (`operations.cc:511-517`): entries a tick already returned but that
        were never performed must not hang."""
        for entry in entries:
            self._fire_callback(entry, False, "shutdown")
            self.handles.mark_done(entry.handle, False,
                                   error="Horovod has been shut down.",
                                   error_cls=ShutdownError)
        for user in users:
            self.handles.mark_done(user, False,
                                   error="Horovod has been shut down.",
                                   error_cls=ShutdownError)

    @staticmethod
    def _fire_callback(entry, ok: bool, payload) -> None:
        if entry.callback:
            try:
                entry.callback(ok, payload)
            except Exception as exc:
                logger.error("completion callback for %r failed: %s",
                             entry.tensor_name, exc)

    def _observe_perform(self, resp: Response, ebr, exact_bytes: int,
                         wire_bytes: int, elapsed: float) -> None:
        """Record one successfully executed response into the registry
        (docs/metrics.md catalog). Runs on the engine thread right after the
        executor returns; all failure paths skip it."""
        op = resp.response_type.name.lower()
        compression = self._executor.last_wire_mode or "none"
        instruments.collective_latency().labels(op=op).observe(elapsed)
        if resp.response_type in (ResponseType.ALLREDUCE,
                                  ResponseType.ADASUM):
            dtype = resp.tensor_dtype or next(
                (str(e.array.dtype) for es in ebr.values() for e in es),
                "unknown")
            instruments.allreduce_latency().labels(
                dtype=dtype, compression=compression).observe(elapsed)
        n_tensors = sum(len(es) for es in ebr.values())
        instruments.fusion_tensors().observe(n_tensors)
        instruments.fusion_bytes().observe(exact_bytes)
        instruments.wire_bytes().labels(compression=compression).inc(
            wire_bytes)
        instruments.wire_bytes_exact().inc(exact_bytes)
        self._wire_acc += wire_bytes
        self._exact_acc += exact_bytes
        if self._exact_acc:
            instruments.quantization_ratio().set(
                self._wire_acc / self._exact_acc)

    # -------------------------------------------------------------- perform
    def _perform(self, resp: Response, pairs) -> None:
        """PerformOperation analogue (`operations.cc:227-304`)."""
        with self._lock:
            entries = [self._pending.pop(ch) for _, ch in pairs]
        if (len(resp.tensor_names) > 1
                and resp.response_type != ResponseType.ERROR
                and any(not e.fusable for e in entries)):
            # bucket-boundary backstop: control planes whose wire/ABI
            # predates the fusable flag (native tick frames, coordinator
            # Requests) can hand back a response that merged client-built
            # buckets. Split it back into per-tensor sub-responses executed
            # in negotiated tensor_names order — deterministic and
            # identical on every rank, because bucket names and flags are
            # produced by the same client code everywhere.
            import dataclasses
            by_name: Dict[str, List[TensorTableEntry]] = {}
            for e in entries:
                by_name.setdefault(e.tensor_name, []).append(e)
            for idx, name in enumerate(resp.tensor_names):
                sub = dataclasses.replace(
                    resp, tensor_names=[name],
                    tensor_sizes=([resp.tensor_sizes[idx]]
                                  if idx < len(resp.tensor_sizes) else []),
                    tensor_shapes=([resp.tensor_shapes[idx]]
                                   if idx < len(resp.tensor_shapes) else []))
                self._perform_resp(sub, by_name.get(name, []))
            return
        self._perform_resp(resp, entries)

    def _perform_resp(self, resp: Response,
                      entries: List[TensorTableEntry]) -> None:
        ebr: Dict[int, List[TensorTableEntry]] = {}
        for e in entries:
            ebr.setdefault(e.rank, []).append(e)
        # order each rank's entries to match resp.names
        name_order = {n: i for i, n in enumerate(resp.tensor_names)}
        for r in ebr:
            ebr[r].sort(key=lambda e: name_order[e.tensor_name])

        tr = _tracing.active()
        if tr is not None:
            # the response arriving IS the end of negotiation for every
            # tensor it fuses
            t_neg = _tracing.clock.trace_us()
            for e in entries:
                tr.mark(e.rank, e.tensor_name, _tracing.T_NEG, t_neg)
                tr.set_fused(e.rank, e.tensor_name, len(entries))

        if resp.response_type == ResponseType.ERROR:
            # enforced-watchdog errors surface as a dedicated type so
            # callers can catch them apart from generic negotiation errors
            # (mirrors the "stall shutdown" prefix idiom in tick())
            msg = resp.error_message or ""
            if msg.startswith("collective timeout"):
                error_cls = CollectiveTimeoutError
                instruments.collective_timeouts().inc()
                bb = _blackbox.active()
                if bb is not None:
                    bb.record(_blackbox.K_TIMEOUT,
                              resp.tensor_names[0] if resp.tensor_names
                              else "", msg)
                    _blackbox.dump(msg)
            else:
                error_cls = HorovodInternalError
                _blackbox.record(_blackbox.K_ERROR, "negotiation", msg)
            for es in ebr.values():
                for e in es:
                    self._fire_callback(e, False, resp.error_message)
                    self.handles.mark_done(e.handle, False,
                                           error=resp.error_message,
                                           error_cls=error_cls)
                    if tr is not None:
                        tr.finish(e.rank, e.tensor_name,
                                  _tracing.clock.trace_us())
            return

        for n in resp.tensor_names:
            self.controller.timeline_op_start(n, resp.response_type.name)
        t0 = time.perf_counter()
        nbytes = sum(int(e.array.size) * e.array.dtype.itemsize
                     for es in ebr.values() for e in es)
        exact_bytes = nbytes
        if tr is not None:
            t_ws = _tracing.clock.trace_us()
            for e in entries:
                tr.mark(e.rank, e.tensor_name, _tracing.T_WIRE_START, t_ws)
        try:
            results = self._executor.execute(resp, ebr)
            if (resp.excluded_ranks and resp.average
                    and resp.response_type == ResponseType.ALLREDUCE
                    and not getattr(self._executor, "partial_aware",
                                    False)):
                # partial collective: the executor zero-filled the excluded
                # slots and divided by the full world; rescale so the mean
                # is over the n_active actual contributors. A partial_aware
                # executor (elastic) divides by the data plane's real
                # participant count and needs no correction.
                import numpy as _np

                n_active = self._world - len(resp.excluded_ranks)
                if n_active > 0:
                    f = self._world / n_active
                    results = {
                        r: [o * _np.asarray(f, o.dtype) for o in outs]
                        for r, outs in results.items()}
            if tr is not None:
                t_we = _tracing.clock.trace_us()
                for e in entries:
                    tr.mark(e.rank, e.tensor_name, _tracing.T_WIRE_END, t_we)
            if self._executor.last_wire_mode:
                # quantized wire: score the bytes actually moved (int8
                # payload + scales; last_wire_bytes is one rank's
                # reduce+gather round, same units as the fp32 accounting
                # above) so autotune learns the compressed economics
                nbytes = (self._executor.last_wire_bytes // 2) * len(ebr)
            self._observe_perform(resp, ebr, exact_bytes, nbytes,
                                  time.perf_counter() - t0)
            for r, es in ebr.items():
                outs = results[r]
                for e, out in zip(es, outs):
                    # callback BEFORE mark_done: completion callbacks (e.g.
                    # the torch in-place copy-back) must be observable by
                    # the time synchronize() unblocks
                    self._fire_callback(e, True, out)
                    self.handles.mark_done(e.handle, True, result=out)
                    if tr is not None:
                        tr.finish(e.rank, e.tensor_name,
                                  _tracing.clock.trace_us())
        except RanksChangedError as exc:
            # membership changed under this response's data exchange: fail
            # its handles with the reset error and re-raise so the loop
            # handler clears the rest of the in-flight set and continues
            msg = str(exc)
            for es in ebr.values():
                for e in es:
                    self._fire_callback(e, False, msg)
                    self.handles.mark_done(e.handle, False, error=msg,
                                           error_cls=type(exc))
                    if tr is not None:
                        tr.finish(e.rank, e.tensor_name,
                                  _tracing.clock.trace_us())
            raise
        except Exception as exc:  # surface execution errors on every handle
            msg = f"{type(exc).__name__}: {exc}"
            for es in ebr.values():
                for e in es:
                    self._fire_callback(e, False, msg)
                    self.handles.mark_done(e.handle, False, error=msg)
                    if tr is not None:
                        tr.finish(e.rank, e.tensor_name,
                                  _tracing.clock.trace_us())
        finally:
            for n in resp.tensor_names:
                self.controller.timeline_op_end(n)
            sig = (int(resp.response_type), nbytes,
                   tuple(sorted(len(es) for es in ebr.values())))
            if sig in self._scored_sigs:
                self.report_score(nbytes, time.perf_counter() - t0)
            else:
                self._scored_sigs.add(sig)  # first run pays jit compile
