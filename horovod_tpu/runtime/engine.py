"""Background collective engine: queue → negotiation → fusion → execute.

Reference parity: the background-thread engine in `horovod/common/operations.cc`
(`BackgroundThreadLoop` :328, `RunLoopOnce` :531, `PerformOperation` :227), the
negotiation protocol in `controller.cc` (`ComputeResponseList` :55,
`ConstructResponse` :358, `FuseResponses` :626, `IncrementTensorCount` :778),
the mutex-protected `TensorQueue` (`tensor_queue.{h,cc}`), and the stall
inspector (`stall_inspector.{h,cc}`).

TPU-native shape: ranks enqueue committed device arrays from their own threads
(cluster mode) or processes; a single engine thread ticks every
``cycle_time_ms``, decides which named tensors all active (non-joined) ranks
have submitted, validates dtype/shape agreement exactly like the coordinator
(ERROR responses on mismatch), fuses ready tensors into ≤ threshold-byte
buckets preserving submission order with lookahead, and hands each fused
Response to the XLA executor. Completion fires per-tensor callbacks and marks
async handles, preserving horovod's out-of-order async semantics.

Env knobs (parity with `common.h:61-87` / `operations.cc:388-485`):
  HOROVOD_FUSION_THRESHOLD (bytes, default 64 MB, operations.cc:404)
  HOROVOD_CYCLE_TIME       (ms,   default 5,     operations.cc:412)
  HOROVOD_STALL_CHECK_TIME_SECONDS (default 60,  stall_inspector.h:75)
  HOROVOD_STALL_SHUTDOWN_TIME_SECONDS (default 0 = never, stall_inspector.h:80)
  HOROVOD_TIMELINE         (path for Chrome-trace output)
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..exceptions import DuplicateNameError, ShutdownError
from .executor import Executor
from .handles import HandleManager
from .messages import Request, RequestType, Response, ResponseType, TensorTableEntry

DEFAULT_FUSION_BYTES = 64 * 1024 * 1024
DEFAULT_CYCLE_MS = 5.0


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


class Engine:
    """One engine per process; owns the negotiation thread and executor."""

    def __init__(self, state):
        self._state = state
        self._world = state.size
        self._mode = state.mode
        self._executor = Executor(state)
        self.handles = HandleManager()

        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        # name -> {rank: TensorTableEntry}; insertion order = negotiation order
        self._table: "OrderedDict[str, Dict[int, TensorTableEntry]]" = OrderedDict()
        self._first_seen: Dict[str, float] = {}
        self._joined: set = set()
        self._join_handles: Dict[int, int] = {}
        self._last_joined: int = -1
        self._shutdown = False
        self._seq = 0
        self._thread: Optional[threading.Thread] = None

        self.fusion_threshold = int(
            _env_float("HOROVOD_FUSION_THRESHOLD", DEFAULT_FUSION_BYTES))
        self.cycle_time_s = _env_float("HOROVOD_CYCLE_TIME", DEFAULT_CYCLE_MS) / 1e3
        self.stall_warning_s = _env_float("HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0)
        self.stall_shutdown_s = _env_float("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0)
        self._stall_warned: set = set()

        from ..utils.timeline import Timeline
        self.timeline = Timeline(os.environ.get("HOROVOD_TIMELINE"))

    # ------------------------------------------------------------------ API
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="hvd_tpu_engine", daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.timeline.close()

    def enqueue(self, entry: TensorTableEntry) -> int:
        """Add a named tensor; returns an async handle.

        Mirrors EnqueueTensorAllreduce/-Allgather/-Broadcast
        (`operations.cc:783-934`) + TensorQueue::AddToTensorQueue duplicate
        detection (`tensor_queue.cc`, DUPLICATE_NAME_ERROR `common.h:160`).
        """
        handle = self.handles.allocate()
        entry.handle = handle
        with self._lock:
            if self._shutdown:
                self.handles.mark_done(
                    handle, False,
                    error="Horovod has been shut down. This was caused by an "
                          "exception on one of the ranks or an earlier shutdown().",
                    error_cls=ShutdownError)
                return handle
            ranks = self._table.setdefault(entry.tensor_name, {})
            if entry.rank in ranks:
                self.handles.mark_done(
                    handle, False,
                    error=f"Duplicate tensor name {entry.tensor_name!r}: a "
                          f"collective with this name from rank {entry.rank} "
                          "is already pending.",
                    error_cls=DuplicateNameError)
                return handle
            self._seq += 1
            entry.enqueue_seq = self._seq
            ranks[entry.rank] = entry
            self._first_seen.setdefault(entry.tensor_name, time.monotonic())
            self.timeline.negotiate_start(entry.tensor_name, entry.rank)
            self._wake.notify_all()
        return handle

    def join(self, rank: int) -> int:
        """Rank signals it has no more data (JoinOp, `operations.cc:908-934`).

        Returns a handle; synchronizing it blocks until ALL ranks joined; the
        result is the id of the last rank to join.
        """
        handle = self.handles.allocate()
        with self._lock:
            self._joined.add(rank)
            self._join_handles[rank] = handle
            self._last_joined = rank
            self._wake.notify_all()
        return handle

    # ----------------------------------------------------------------- loop
    def _required_ranks(self) -> set:
        if self._mode == "multiprocess":
            return {self._state.rank0}
        return set(range(self._world))

    def _loop(self) -> None:
        while True:
            try:
                with self._lock:
                    if not self._shutdown and not self._table and not self._joined:
                        self._wake.wait(timeout=self.cycle_time_s)
                    if self._shutdown:
                        self._drain_locked()
                        return
                    responses, entries = self._compute_responses_locked()
                for resp, ebr in zip(responses, entries):
                    self._perform(resp, ebr)
                if not responses:
                    # nothing ready: nap one cycle (RunLoopOnce cadence)
                    time.sleep(self.cycle_time_s / 5)
            except Exception as exc:
                # An engine-tick failure (e.g. stall-shutdown) must not leave
                # callers blocked: fail everything outstanding and stop, the
                # way the reference drains with SHUT_DOWN_ERROR
                # (`operations.cc:511-517`).
                import logging
                logging.getLogger("horovod_tpu").error(
                    "engine thread aborting: %s", exc)
                with self._lock:
                    self._shutdown = True
                    self._drain_locked()
                return

    def _drain_locked(self) -> None:
        """Finalize outstanding entries with shutdown error
        (`operations.cc:511-517`)."""
        for name, ranks in self._table.items():
            for e in ranks.values():
                self.handles.mark_done(
                    e.handle, False, error="Horovod has been shut down.")
                if e.callback:
                    e.callback(False, "shutdown")
        self._table.clear()
        for r, h in self._join_handles.items():
            self.handles.mark_done(h, False, error="Horovod has been shut down.")
        self._join_handles.clear()

    # ------------------------------------------------------ negotiation tick
    def _compute_responses_locked(self):
        """ComputeResponseList analogue: find ready names, validate, fuse."""
        required = self._required_ranks()
        active = required - self._joined
        now = time.monotonic()

        # all ranks joined -> release join barrier (controller.cc:202-256)
        if self._joined and self._joined >= required and not self._table:
            for r, h in self._join_handles.items():
                self.handles.mark_done(h, True, result=self._last_joined)
            self._join_handles.clear()
            self._joined.clear()

        ready: List[str] = []
        for name, ranks in self._table.items():
            # ready when every active (non-joined) rank has submitted; with an
            # empty active set (everyone joined) pending tensors reduce
            # against zeros from the joined ranks (controller.cc:202-256)
            if active <= set(ranks.keys()):
                ready.append(name)
            else:
                self._check_stall(name, now)

        responses: List[Response] = []
        out_entries: List[Dict[int, List[TensorTableEntry]]] = []

        # validate each ready name -> single-name response or error
        singles: List[tuple] = []  # (name, rtype, dtype, bytes, entries_by_rank)
        for name in ready:
            ranks = self._table.pop(name)
            self._first_seen.pop(name, None)
            self._stall_warned.discard(name)
            err = self._validate(name, ranks)
            if err is not None:
                resp = Response(ResponseType.ERROR, [name], error_message=err)
                responses.append(resp)
                out_entries.append({r: [e] for r, e in ranks.items()})
                continue
            e0 = next(iter(ranks.values()))
            rtype = e0.request_type
            nbytes = int(sum(
                np.prod(e.array.shape) * e.array.dtype.itemsize
                for e in ranks.values())) or 1
            singles.append((name, e0, rtype, str(e0.array.dtype), nbytes, ranks))

        # fusion: greedy buckets by (type, dtype, scale/average/root signature)
        # preserving order, with lookahead past non-matching entries
        # (FuseResponses, controller.cc:626-750).
        # In multiprocess mode fusion is DISABLED until the cross-process
        # control plane lands: bucket contents would depend on per-process
        # tick timing, and all processes must execute identical XLA programs.
        fuse_ok = self._mode != "multiprocess"
        used = [False] * len(singles)
        for i, (name, e0, rtype, dtype, nbytes, ranks) in enumerate(singles):
            if used[i]:
                continue
            used[i] = True
            bucket = [i]
            total = nbytes
            if fuse_ok and rtype in (RequestType.ALLREDUCE, RequestType.ADASUM,
                                     RequestType.ALLGATHER):
                sig = self._fusion_sig(singles[i])
                for j in range(i + 1, len(singles)):
                    if used[j]:
                        continue
                    if self._fusion_sig(singles[j]) == sig and \
                            total + singles[j][4] <= self.fusion_threshold:
                        used[j] = True
                        bucket.append(j)
                        total += singles[j][4]
            names = [singles[k][0] for k in bucket]
            rt = ResponseType(int(rtype))
            resp = Response(rt, names)
            if rtype == RequestType.ALLREDUCE:
                resp.average = e0.average
            ebr: Dict[int, List[TensorTableEntry]] = {}
            for k in bucket:
                for r, e in singles[k][5].items():
                    ebr.setdefault(r, []).append(e)
            responses.append(resp)
            out_entries.append(ebr)
        if responses:
            self.timeline.cycle_tick()  # one CYCLE marker per engine tick
        return responses, out_entries

    @staticmethod
    def _fusion_sig(single):
        name, e0, rtype, dtype, nbytes, ranks = single
        return (int(rtype), dtype, e0.average,
                e0.prescale_factor, e0.postscale_factor, e0.root_rank)

    def _validate(self, name: str, ranks: Dict[int, TensorTableEntry]) -> Optional[str]:
        """ConstructResponse-style cross-rank consistency checks
        (`controller.cc:358-597`)."""
        entries = list(ranks.values())
        e0 = entries[0]
        if (self._mode == "multiprocess" and self._world > 1
                and e0.request_type == RequestType.ALLGATHER):
            # per-rank dim0 sizes live on other processes; needs the
            # cross-process control plane (negotiation over DCN) to agree on
            # the ragged layout. Allreduce/broadcast/alltoall are symmetric
            # and need no size exchange.
            return ("Allgather is not yet supported in multiprocess mode "
                    "(cross-process size negotiation not implemented).")
        if any(e.request_type != e0.request_type for e in entries):
            types = {e.rank: e.request_type.name for e in entries}
            return (f"Mismatched collective operations for tensor {name!r}: "
                    f"{types}")
        if any(str(e.array.dtype) != str(e0.array.dtype) for e in entries):
            dts = {e.rank: str(e.array.dtype) for e in entries}
            return f"Mismatched data types for tensor {name!r}: {dts}"
        if any((e.average, e.prescale_factor, e.postscale_factor)
               != (e0.average, e0.prescale_factor, e0.postscale_factor)
               for e in entries):
            flags = {e.rank: ("avg" if e.average else "sum",
                              e.prescale_factor, e.postscale_factor)
                     for e in entries}
            return (f"Mismatched reduction op/scale factors for tensor "
                    f"{name!r}: {flags}")
        if e0.request_type in (RequestType.ALLREDUCE, RequestType.ADASUM,
                               RequestType.BROADCAST, RequestType.ALLTOALL):
            if any(tuple(e.array.shape) != tuple(e0.array.shape) for e in entries):
                shps = {e.rank: tuple(e.array.shape) for e in entries}
                return f"Mismatched tensor shapes for {name!r}: {shps}"
        if e0.request_type == RequestType.ALLGATHER:
            if any(tuple(e.array.shape[1:]) != tuple(e0.array.shape[1:])
                   for e in entries):
                shps = {e.rank: tuple(e.array.shape) for e in entries}
                return (f"Mismatched allgather tensor shapes beyond first "
                        f"dimension for {name!r}: {shps}")
            if any(e.array.ndim == 0 for e in entries):
                return f"Allgather of scalar tensor {name!r} is not supported."
        if e0.request_type == RequestType.ADASUM:
            if self._world & (self._world - 1):
                # parity: torch/mpi_ops.py:104-120 (power-of-2 requirement)
                return (f"Adasum requires a power-of-2 number of ranks; got "
                        f"{self._world}.")
        if e0.request_type == RequestType.ALLTOALL:
            d0 = e0.array.shape[0] if e0.array.ndim else 0
            if e0.array.ndim == 0 or d0 % self._world != 0:
                return (f"Alltoall tensor {name!r} first dimension ({d0}) "
                        f"must be divisible by world size {self._world}.")
        if e0.request_type == RequestType.BROADCAST:
            if any(e.root_rank != e0.root_rank for e in entries):
                roots = {e.rank: e.root_rank for e in entries}
                return f"Mismatched root ranks for broadcast {name!r}: {roots}"
            if not (0 <= e0.root_rank < self._world):
                return (f"Invalid root rank {e0.root_rank} for broadcast "
                        f"{name!r} (world size {self._world}).")
        if self._joined and e0.request_type in (RequestType.ALLGATHER,
                                                RequestType.BROADCAST,
                                                RequestType.ALLTOALL):
            # parity: controller.cc:434-437, 510-513
            return (f"{e0.request_type.name} is not supported while a rank "
                    "has joined.")
        return None

    def _check_stall(self, name: str, now: float) -> None:
        """StallInspector warn/shutdown (`stall_inspector.{h,cc}`)."""
        t0 = self._first_seen.get(name)
        if t0 is None:
            return
        waited = now - t0
        if waited > self.stall_warning_s and name not in self._stall_warned:
            self._stall_warned.add(name)
            missing = sorted(self._required_ranks() - self._joined
                             - set(self._table[name].keys()))
            import logging
            logging.getLogger("horovod_tpu").warning(
                "One or more tensors were submitted to be reduced/gathered/"
                "broadcasted by subset of ranks and are waiting for remainder "
                "of ranks for more than %ds. Stalled op: %s (missing ranks: %s)",
                int(self.stall_warning_s), name, missing)
        if self.stall_shutdown_s and waited > self.stall_shutdown_s:
            raise RuntimeError(
                f"Stalled tensor {name!r} exceeded "
                f"HOROVOD_STALL_SHUTDOWN_TIME_SECONDS; aborting "
                "(stall_inspector.h:80).")

    # -------------------------------------------------------------- perform
    def _perform(self, resp: Response, ebr: Dict[int, List[TensorTableEntry]]):
        """PerformOperation analogue (`operations.cc:227-304`)."""
        names = resp.tensor_names
        if resp.response_type == ResponseType.ERROR:
            for r, es in ebr.items():
                for e in es:
                    self.handles.mark_done(e.handle, False,
                                           error=resp.error_message)
                    if e.callback:
                        e.callback(False, resp.error_message)
            return
        for n in names:
            self.timeline.op_start(n, resp.response_type.name)
        try:
            results = self._executor.execute(resp, ebr, frozenset(self._joined))
            for r, es in ebr.items():
                outs = results[r]
                for e, out in zip(es, outs):
                    self.handles.mark_done(e.handle, True, result=out)
                    if e.callback:
                        e.callback(True, out)
        except Exception as exc:  # surface execution errors on every handle
            msg = f"{type(exc).__name__}: {exc}"
            for r, es in ebr.items():
                for e in es:
                    self.handles.mark_done(e.handle, False, error=msg)
                    if e.callback:
                        e.callback(False, msg)
        finally:
            for n in names:
                self.timeline.op_end(n)
