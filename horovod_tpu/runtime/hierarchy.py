"""Hierarchical negotiation: the per-host sub-coordinator tier.

Flat mode puts every rank on its own TCP connection to rank 0, which makes
rank 0's negotiation work O(world) frames per round — fine at 32 ranks,
a storm at 1024. With ``HOROVOD_HIERARCHICAL_COORD`` set, each host's
local-rank-0 process runs a :class:`SubCoordinator`: local ranks speak the
UNCHANGED downstream protocol (HELLO/LIST/RESP/HEARTBEAT/BYE) to it over
loopback, and the sub-coordinator ships ONE ``MSG_BATCH`` frame per round
upstream to rank 0, carrying every local rank's request list as a
``(rank, seq, payload)`` entry. Rank 0 answers with ``MSG_BATCH_RESP``
frames whose entries self-identify the same way, so responses need no 1:1
frame pairing — deferred joiner admissions ship later as single-entry
frames. Rank 0's per-round work drops to O(hosts).

The batching core (:class:`HostAggregator`) is deliberately socketless so
tests and benchmarks can drive thousands of fake ranks through it
in-process; :class:`SubCoordinator` is the thin TCP shell around it.

Liveness is vouched per host: the sub-coordinator sends ``MSG_BATCH_HB``
listing its currently-connected local ranks; a rank missing from the list
(its local connection died) enters the coordinator's ordinary reconnect
grace window, exactly as a flat-mode connection loss would.

See docs/control-plane.md.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Callable, Dict, List, Set, Tuple

from ..utils.env import env_float as _env_float
from . import wire
from .coordinator import (MSG_BATCH, MSG_BATCH_HB, MSG_BATCH_RESP,
                          MSG_BLACKBOX, MSG_BYE, MSG_HEARTBEAT, MSG_HELLO,
                          MSG_LIST, MSG_METRICS, MSG_RESP, MSG_RESUME,
                          MSG_TRACE, _backoff_schedule)
from ..exceptions import ShutdownError

logger = logging.getLogger("horovod_tpu")

Entry = Tuple[int, int, bytes]  # (rank, seq, payload)


class AggregatorClosed(ConnectionError):
    """The sub-coordinator is shutting down (or lost rank 0 for good);
    subclassing ConnectionError lets the worker's ordinary reconnect path
    handle the downstream connection teardown that follows."""


class HostAggregator:
    """Socketless batching core: collects one control frame per local rank,
    releases them upstream as a single batch, and routes the entries of
    whatever response frames come back to the blocked submitters.

    A batch flushes when every rank currently expected to tick has
    deposited a frame, or when ``linger_s`` elapses after the first
    deposit — whichever comes first. Ranks with an entry already in flight
    upstream (deferred joiners blocked in admission) are not waited for,
    so one slow admission never adds linger latency to the members' rounds.
    """

    def __init__(self, flush_fn: Callable[[List[Entry]], None],
                 linger_s: float = 0.005):
        self._flush_fn = flush_fn
        self._linger_s = linger_s
        self._cv = threading.Condition()
        self._ranks: Set[int] = set()          # ranks with a live local conn
        self._awaiting: Set[int] = set()       # ranks with an entry upstream
        self._pending: Dict[int, Tuple[int, bytes]] = {}  # rank -> (seq, pl)
        self._replies: Dict[Tuple[int, int], bytes] = {}
        self._first_t = 0.0
        self._closed = False
        self.flushes = 0  # batches shipped (test observability)

    def register(self, rank: int) -> None:
        with self._cv:
            self._ranks.add(rank)
            self._cv.notify_all()

    def unregister(self, rank: int) -> None:
        with self._cv:
            self._ranks.discard(rank)
            self._awaiting.discard(rank)
            self._pending.pop(rank, None)
            self._cv.notify_all()

    def ranks(self) -> List[int]:
        with self._cv:
            return sorted(self._ranks)

    def submit(self, rank: int, seq: int, payload: bytes) -> bytes:
        """Deposit one rank's frame and block until its reply arrives.
        Strict request/reply per rank upstream of this call means at most
        one live entry per rank; a duplicate (rank, seq) after a local
        reconnect simply re-ships, and the coordinator's replay cache makes
        that idempotent."""
        key = (rank, seq)
        with self._cv:
            if self._closed:
                raise AggregatorClosed("sub-coordinator shut down")
            self._pending[rank] = (seq, payload)
            if self._first_t == 0.0:
                self._first_t = time.monotonic()
            self._cv.notify_all()
        while True:
            batch = self._take_due_batch()
            if batch:
                # network I/O happens outside the lock; whichever submitter
                # wins the pop ships the whole host's round
                self._flush_fn(batch)
            with self._cv:
                if key in self._replies:
                    return self._replies.pop(key)
                if self._closed:
                    raise AggregatorClosed("sub-coordinator shut down")
                self._cv.wait(timeout=0.005)

    def _take_due_batch(self) -> List[Entry]:
        with self._cv:
            if not self._pending:
                return []
            waiting_for = self._ranks - self._awaiting
            full = bool(waiting_for) and set(self._pending) >= waiting_for
            lingered = (self._first_t > 0.0 and
                        time.monotonic() - self._first_t >= self._linger_s)
            if not (full or lingered):
                return []
            entries = [(r, s, p)
                       for r, (s, p) in sorted(self._pending.items())]
            self._pending.clear()
            self._first_t = 0.0
            self._awaiting.update(r for r, _, _ in entries)
            self.flushes += 1
            return entries

    def deliver(self, rank: int, seq: int, data: bytes) -> None:
        with self._cv:
            self._awaiting.discard(rank)
            self._replies[(rank, seq)] = data
            self._cv.notify_all()

    def inflight(self) -> List[Entry]:
        """Entries shipped upstream with no reply yet — what a reconnect
        must re-send. Payloads are not retained here; see SubCoordinator's
        inflight ledger (this accessor reports ranks only for tests)."""
        with self._cv:
            return sorted(self._awaiting)  # type: ignore[return-value]

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


class SubCoordinator:
    """Per-host relay: downstream server speaking the flat worker protocol
    to local ranks, one upstream connection to rank 0 speaking batches."""

    def __init__(self, up_host: str, up_port: int, secret: str,
                 leader_rank: int, host: str = "127.0.0.1"):
        self._up_addr = (up_host, up_port)
        self._secret = secret
        self._leader = leader_rank
        self._stop = threading.Event()
        self._jitter = _env_float("HOROVOD_RECONNECT_JITTER", 0.0)
        self._hb_interval = _env_float("HOROVOD_HEARTBEAT_INTERVAL", 5.0)
        linger = _env_float("HOROVOD_HIERARCHY_LINGER_MS", 5.0) / 1000.0
        self.agg = HostAggregator(self._ship, linger_s=linger)
        # entries shipped upstream and not yet answered: the reconnect path
        # re-sends them all (idempotent via the coordinator replay caches)
        self._inflight: Dict[Tuple[int, int], bytes] = {}
        self._inflight_lock = threading.Lock()
        self._bseq = 0
        self._up_send_lock = threading.Lock()
        self._up = self._dial_upstream(MSG_HELLO)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._accept_loop, name="hvd_sub_accept",
                         daemon=True).start()
        threading.Thread(target=self._recv_loop, name="hvd_sub_upstream",
                         daemon=True).start()
        if self._hb_interval > 0:
            threading.Thread(target=self._hb_loop, name="hvd_sub_hb",
                             daemon=True).start()

    # --------------------------------------------------------------- upstream
    def _dial_upstream(self, hello_type: int) -> socket.socket:
        sock = socket.create_connection(self._up_addr, timeout=5)
        sock.settimeout(0.5)
        payload = (wire.encode_resume(-1) if hello_type == MSG_RESUME
                   else b"")
        wire.send_frame(sock, self._secret, hello_type, 0, self._leader,
                        payload)
        return sock

    def _next_bseq(self) -> int:
        with self._inflight_lock:
            self._bseq += 1
            return self._bseq

    def _ship(self, entries: List[Entry]) -> None:
        """HostAggregator flush hook: record the entries as in flight, then
        send one MSG_BATCH. Send errors are swallowed — the upstream recv
        loop owns reconnect, and reconnect re-ships the inflight ledger."""
        with self._inflight_lock:
            for r, s, p in entries:
                self._inflight[(r, s)] = p
        self._send_batch(entries)

    def _send_batch(self, entries: List[Entry]) -> None:
        payload = wire.encode_batched_entries(entries)
        try:
            with self._up_send_lock:
                wire.send_frame(self._up, self._secret, MSG_BATCH,
                                self._next_bseq(), self._leader, payload)
        except (ConnectionError, OSError):
            pass

    def _forward(self, mt: int, rank: int, payload: bytes) -> None:
        """Fire-and-forget relay of telemetry/BYE frames, rank preserved."""
        try:
            with self._up_send_lock:
                wire.send_frame(self._up, self._secret, mt, 0, rank, payload)
        except (ConnectionError, OSError):
            pass

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                mt, _, _, payload = wire.recv_frame(self._up, self._secret,
                                                    self._stop)
            except ShutdownError:
                return
            except (ConnectionError, OSError) as exc:
                if self._stop.is_set():
                    return
                if not self._reconnect_upstream(exc):
                    logger.warning(
                        "sub-coordinator (leader rank %d): rank 0 stayed "
                        "unreachable; releasing local ranks", self._leader)
                    self.agg.close()
                    return
                continue
            if mt == MSG_BATCH_RESP:
                for rank, seq, data in wire.decode_batched_entries(payload):
                    with self._inflight_lock:
                        self._inflight.pop((rank, seq), None)
                    self.agg.deliver(rank, seq, data)
            elif mt == MSG_BYE:
                self.agg.close()
                return
            # anything else on the upstream socket is ignored: the batch
            # protocol owns this connection

    def _reconnect_upstream(self, why: Exception) -> bool:
        for attempt in range(1, 9):
            delay = _backoff_schedule(self._leader, attempt, 0.05, 2.0,
                                      self._jitter)
            if self._stop.wait(delay):
                return False
            try:
                sock = self._dial_upstream(MSG_RESUME)
            except (ConnectionError, OSError):
                continue
            with self._up_send_lock:
                old, self._up = self._up, sock
            try:
                old.close()
            except OSError:
                pass
            with self._inflight_lock:
                entries = [(r, s, p)
                           for (r, s), p in sorted(self._inflight.items())]
            if entries:
                self._send_batch(entries)
            logger.warning(
                "sub-coordinator (leader rank %d): reconnected upstream "
                "after %s (attempt %d, re-shipped %d inflight entries)",
                self._leader, why, attempt, len(entries))
            return True
        return False

    def _hb_loop(self) -> None:
        while not self._stop.wait(self._hb_interval):
            alive = self.agg.ranks()
            if not alive:
                continue
            try:
                with self._up_send_lock:
                    wire.send_frame(self._up, self._secret, MSG_BATCH_HB, 0,
                                    self._leader,
                                    wire.encode_batched_heartbeat(alive))
            except (ConnectionError, OSError):
                pass  # recv loop owns reconnect

    # ------------------------------------------------------------- downstream
    def _accept_loop(self) -> None:
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(0.5)
            threading.Thread(target=self._serve, args=(conn,),
                             name="hvd_sub_conn", daemon=True).start()

    def _serve(self, conn) -> None:
        rank = -1
        try:
            mt, _, rank, _ = wire.recv_frame(conn, self._secret, self._stop)
            if mt not in (MSG_HELLO, MSG_RESUME):
                raise ConnectionError(
                    f"sub-coordinator expected HELLO/RESUME, got {mt}")
            # a RESUME needs no upstream replay here: the worker re-sends
            # its in-flight frame itself, and submit() re-ships it
            self.agg.register(rank)
            while True:
                mt, seq, rank, payload = wire.recv_frame(conn, self._secret,
                                                         self._stop)
                if mt == MSG_BYE:
                    # global shutdown: rank 0 sets bye and tears this
                    # host's upstream down; locals see shutdown responses
                    self._forward(MSG_BYE, rank, b"")
                    return
                if mt == MSG_HEARTBEAT:
                    # local liveness is the open connection itself; the
                    # periodic MSG_BATCH_HB vouches for it upstream
                    continue
                if mt in (MSG_METRICS, MSG_TRACE, MSG_BLACKBOX):
                    self._forward(mt, rank, payload)
                    continue
                if mt != MSG_LIST:
                    # DATA/CLOCK bypass the hierarchy on direct rank-0
                    # connections; seeing one here is a protocol bug
                    raise ConnectionError(
                        f"sub-coordinator: unexpected message type {mt}")
                data = self.agg.submit(rank, seq, payload)
                wire.send_frame(conn, self._secret, MSG_RESP, seq, 0, data)
        except ShutdownError:
            pass
        except (ConnectionError, OSError):
            pass
        finally:
            if rank >= 0:
                self.agg.unregister(rank)
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        self.agg.close()
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            self._up.close()
        except OSError:
            pass
