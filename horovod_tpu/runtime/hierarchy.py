"""Hierarchical negotiation: the N-tier sub-coordinator tree.

Flat mode puts every rank on its own TCP connection to rank 0, which makes
rank 0's negotiation work O(world) frames per round — fine at 32 ranks,
a storm at 1024. With ``HOROVOD_HIERARCHICAL_COORD`` set, each host's
local-rank-0 process runs a :class:`SubCoordinator`: local ranks speak the
UNCHANGED downstream protocol (HELLO/LIST/RESP/HEARTBEAT/BYE) to it over
loopback, and the sub-coordinator ships ONE batched frame per round
upstream. With one tier (the default) that frame is ``MSG_BATCH`` carrying
per-rank ``(rank, seq, payload)`` entries and rank 0's work is O(hosts).

``HOROVOD_HIERARCHY_TIERS`` >= 2 stacks more aggregation tiers between the
hosts and rank 0 (host -> slice -> pod, fanout per tier from
``HOROVOD_HIERARCHY_FANOUT``). Above the host tier, per-rank entries stop
scaling, so tier frames (``MSG_TBATCH``) carry GROUPS — one
``(seq, payload, runs)`` per distinct payload, where ``runs`` run-length
encodes every rank that submitted those bytes. In steady state a whole
subtree collapses to one group, each tier merges its children's groups in
O(children), and rank 0 sees O(fanout) frames AND O(fanout) work per round
regardless of world size.

The batching cores are deliberately socketless so tests and benchmarks can
drive thousands of fake ranks through them in-process:
:class:`HostAggregator` (blocking per-rank submit, the host tier) and
:class:`GroupAggregator` (async group relay, the mid tiers);
:class:`SubCoordinator` is the thin TCP shell around either.

Liveness is vouched per subtree: the host tier sends ``MSG_BATCH_HB``
(one tier) or ``MSG_THB`` (N tiers) listing its connected ranks; mid
tiers merge their children's vouches into one run list. A rank missing
from the vouch enters the coordinator's ordinary reconnect grace window.

Failover is per tier: mid-tier aggregators are STATELESS relays (every
durable artifact lives below them, in each host's in-flight ledger, or
above them, in rank 0's replay shards and the replicated membership
journal), so a :class:`TierStandby` just watches its primary's TCP
liveness and on sustained death starts a replacement, publishing
``addr.{gen}.t{tier}.{index}.f{n}``. Children probe that key — and the
root standby's ``addr.{gen}.f{n}`` — from their upstream-reconnect path
and re-ship their ledgers; replay dedupe upstream makes that idempotent.

See docs/control-plane.md.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from .. import blackbox as _blackbox
from ..exceptions import ShutdownError
from ..metrics import instruments
from ..utils.env import env_float as _env_float
from . import wire
from .coordinator import (MSG_BATCH, MSG_BATCH_HB, MSG_BATCH_RESP,
                          MSG_BLACKBOX, MSG_BYE, MSG_FENCED, MSG_HEARTBEAT,
                          MSG_HELLO, MSG_LIST, MSG_METRICS, MSG_RESP,
                          MSG_RESUME, MSG_TBATCH, MSG_TBATCH_RESP, MSG_THB,
                          MSG_TRACE, _backoff_schedule, _publish_key,
                          _resolve_key)

logger = logging.getLogger("horovod_tpu")

Entry = Tuple[int, int, bytes]        # (rank, seq, payload)
Group = Tuple[int, bytes, wire.Runs]  # (seq, payload, runs)


def parse_tier_config() -> Tuple[int, int]:
    """(tiers, fanout) from HOROVOD_HIERARCHY_TIERS/HOROVOD_HIERARCHY_FANOUT.

    tiers=1 (default) is the PR-9 single host tier with the legacy
    MSG_BATCH wire; fanout only matters from 2 tiers up (children per
    aggregator at every tier above the hosts, default 8)."""
    tiers = max(1, int(os.environ.get("HOROVOD_HIERARCHY_TIERS", "1")
                       or "1"))
    fanout = int(os.environ.get("HOROVOD_HIERARCHY_FANOUT", "8") or "8")
    return tiers, max(2, fanout)


def coalesce_entries(entries: List[Entry]) -> List[Group]:
    """Collapse per-rank entries into payload-identical groups (first-seen
    order); the host tier's O(local ranks) -> O(distinct payloads) step."""
    buckets: Dict[Tuple[int, bytes], List[int]] = {}
    order: List[Tuple[int, bytes]] = []
    for rank, seq, payload in entries:
        key = (seq, payload)
        if key not in buckets:
            buckets[key] = []
            order.append(key)
        buckets[key].append(rank)
    return [(seq, payload, wire.ranks_to_runs(buckets[(seq, payload)]))
            for seq, payload in order]


def merge_group_batches(batches: List[List[Group]]) -> List[Group]:
    """Union children's group batches: identical (seq, payload) groups
    merge their run lists. The whole per-round cost of a mid tier."""
    merged: Dict[Tuple[int, bytes], wire.Runs] = {}
    order: List[Tuple[int, bytes]] = []
    for groups in batches:
        for seq, payload, runs in groups:
            key = (seq, payload)
            if key not in merged:
                merged[key] = runs
                order.append(key)
            else:
                merged[key] = wire.merge_runs(merged[key], runs)
    return [(seq, payload, merged[(seq, payload)])
            for seq, payload in order]


class AggregatorClosed(ConnectionError):
    """The sub-coordinator is shutting down (or lost rank 0 for good);
    subclassing ConnectionError lets the worker's ordinary reconnect path
    handle the downstream connection teardown that follows."""


class HostAggregator:
    """Socketless batching core: collects one control frame per local rank,
    releases them upstream as a single batch, and routes the entries of
    whatever response frames come back to the blocked submitters.

    A batch flushes when every rank currently expected to tick has
    deposited a frame, or when ``linger_s`` elapses after the first
    deposit — whichever comes first. Ranks with an entry already in flight
    upstream (deferred joiners blocked in admission) are not waited for,
    so one slow admission never adds linger latency to the members' rounds.
    """

    def __init__(self, flush_fn: Callable[[List[Entry]], None],
                 linger_s: float = 0.005):
        self._flush_fn = flush_fn
        self._linger_s = linger_s
        self._cv = threading.Condition()
        self._ranks: Set[int] = set()          # ranks with a live local conn
        self._awaiting: Set[int] = set()       # ranks with an entry upstream
        self._pending: Dict[int, Tuple[int, bytes]] = {}  # rank -> (seq, pl)
        self._replies: Dict[Tuple[int, int], bytes] = {}
        self._first_t = 0.0
        self._closed = False
        self.flushes = 0  # batches shipped (test observability)

    def register(self, rank: int) -> None:
        with self._cv:
            self._ranks.add(rank)
            self._cv.notify_all()

    def unregister(self, rank: int) -> None:
        with self._cv:
            self._ranks.discard(rank)
            self._awaiting.discard(rank)
            self._pending.pop(rank, None)
            self._cv.notify_all()

    def ranks(self) -> List[int]:
        with self._cv:
            return sorted(self._ranks)

    def submit(self, rank: int, seq: int, payload: bytes) -> bytes:
        """Deposit one rank's frame and block until its reply arrives.
        Strict request/reply per rank upstream of this call means at most
        one live entry per rank; a duplicate (rank, seq) after a local
        reconnect simply re-ships, and the coordinator's replay cache makes
        that idempotent."""
        key = (rank, seq)
        with self._cv:
            if self._closed:
                raise AggregatorClosed("sub-coordinator shut down")
            self._pending[rank] = (seq, payload)
            if self._first_t == 0.0:
                self._first_t = time.monotonic()
            self._cv.notify_all()
        while True:
            batch = self._take_due_batch()
            if batch:
                # network I/O happens outside the lock; whichever submitter
                # wins the pop ships the whole host's round
                self._flush_fn(batch)
            with self._cv:
                if key in self._replies:
                    return self._replies.pop(key)
                if self._closed:
                    raise AggregatorClosed("sub-coordinator shut down")
                self._cv.wait(timeout=0.005)

    def _take_due_batch(self) -> List[Entry]:
        with self._cv:
            if not self._pending:
                return []
            waiting_for = self._ranks - self._awaiting
            full = bool(waiting_for) and set(self._pending) >= waiting_for
            lingered = (self._first_t > 0.0 and
                        time.monotonic() - self._first_t >= self._linger_s)
            if not (full or lingered):
                return []
            entries = [(r, s, p)
                       for r, (s, p) in sorted(self._pending.items())]
            self._pending.clear()
            self._first_t = 0.0
            self._awaiting.update(r for r, _, _ in entries)
            self.flushes += 1
            return entries

    def deliver(self, rank: int, seq: int, data: bytes) -> None:
        with self._cv:
            self._awaiting.discard(rank)
            self._replies[(rank, seq)] = data
            self._cv.notify_all()

    def inflight(self) -> List[Entry]:
        """Entries shipped upstream with no reply yet — what a reconnect
        must re-send. Payloads are not retained here; see SubCoordinator's
        inflight ledger (this accessor reports ranks only for tests)."""
        with self._cv:
            return sorted(self._awaiting)  # type: ignore[return-value]

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


class GroupAggregator:
    """The HostAggregator machinery one tier up: children are whole
    aggregators, deposits are groups, and replies route back by run
    intersection instead of unblocking per-rank submitters. Deposits never
    block — a mid-tier relay must keep reading its children's heartbeats
    while a round is in flight — so flushing is driven by deposits plus
    the owning SubCoordinator's linger ticker.

    The in-flight ledger lives here as (child, seq, payload, runs) rows:
    an upstream reconnect re-ships their merged union, and a response
    group subtracts the runs it covered so an elastic partial answer
    (member runs now, deferred joiner singles later) leaves exactly the
    unanswered remainder eligible for re-ship."""

    def __init__(self, flush_fn: Callable[[List[Group]], None],
                 linger_s: float = 0.005):
        self._flush_fn = flush_fn
        self._linger_s = linger_s
        self._cv = threading.Condition()
        # child key -> reply_fn(groups, entries); keys are child leader ranks
        self._children: Dict[int, Callable] = {}
        self._pending: Dict[int, List[Group]] = {}
        self._inflight: List[Tuple[int, int, bytes, wire.Runs]] = []
        self._first_t = 0.0
        self._closed = False
        self.flushes = 0

    def register(self, child: int, reply_fn: Callable) -> None:
        with self._cv:
            self._children[child] = reply_fn
            self._cv.notify_all()

    def unregister(self, child: int) -> None:
        with self._cv:
            self._children.pop(child, None)
            self._pending.pop(child, None)
            # in-flight rows stay: the child re-homes (to us or to our
            # standby) and re-ships; replay dedupe upstream absorbs both
            self._cv.notify_all()

    def deposit(self, child: int, groups: List[Group]) -> None:
        with self._cv:
            if self._closed:
                raise AggregatorClosed("tier aggregator shut down")
            self._pending.setdefault(child, []).extend(groups)
            if self._first_t == 0.0:
                self._first_t = time.monotonic()
        self.maybe_flush()

    def maybe_flush(self) -> None:
        with self._cv:
            if not self._pending or self._closed:
                return
            awaiting = {row[0] for row in self._inflight}
            waiting_for = set(self._children) - awaiting
            full = bool(waiting_for) and set(self._pending) >= waiting_for
            lingered = (self._first_t > 0.0 and
                        time.monotonic() - self._first_t >= self._linger_s)
            if not (full or lingered):
                return
            batches = [self._pending[c] for c in sorted(self._pending)]
            for child in sorted(self._pending):
                for seq, payload, runs in self._pending[child]:
                    self._inflight.append((child, seq, payload, runs))
            self._pending.clear()
            self._first_t = 0.0
            self.flushes += 1
            merged = merge_group_batches(batches)
        self._flush_fn(merged)  # network I/O outside the lock

    def deliver_groups(self, rgroups: List[Group]) -> None:
        """Route upstream response groups downstream by run intersection."""
        out: Dict[int, List[Group]] = {}
        with self._cv:
            for seq, data, runs in rgroups:
                kept = []
                for row in self._inflight:
                    child, eseq, payload, eruns = row
                    if eseq != seq:
                        kept.append(row)
                        continue
                    inter = wire.runs_intersect(eruns, runs)
                    if not inter:
                        kept.append(row)
                        continue
                    out.setdefault(child, []).append((seq, data, inter))
                    left = wire.runs_subtract(eruns, inter)
                    if left:
                        kept.append((child, eseq, payload, left))
                self._inflight = kept
            fns = {c: self._children.get(c) for c in out}
            self._cv.notify_all()
        for child, groups in out.items():
            fn = fns.get(child)
            if fn is not None:
                fn(groups, [])

    def deliver_entry(self, rank: int, seq: int, data: bytes) -> None:
        """Route one deferred per-rank entry (elastic joiner admission)."""
        target = None
        with self._cv:
            kept = []
            for row in self._inflight:
                child, eseq, payload, eruns = row
                if (target is None and eseq == seq
                        and wire.runs_contain(eruns, rank)):
                    target = child
                    left = wire.runs_subtract(eruns, [(rank, 1)])
                    if left:
                        kept.append((child, eseq, payload, left))
                else:
                    kept.append(row)
            self._inflight = kept
            fn = self._children.get(target) if target is not None else None
            self._cv.notify_all()
        if fn is not None:
            fn([], [(rank, seq, data)])

    def inflight_merged(self) -> List[Group]:
        """Unanswered groups across all children — the reconnect re-ship."""
        with self._cv:
            rows = [(seq, payload, runs)
                    for _, seq, payload, runs in self._inflight]
        return merge_group_batches([rows])

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


class SubCoordinator:
    """Per-node relay: downstream server speaking the flat worker protocol
    (tier 1) or the group protocol (tiers >= 2) to its children, one
    upstream connection speaking batches.

    ``tier``/``index`` name this aggregator's slot in the tree; ``tiers``
    is the total depth — upstream frames are the legacy per-rank
    ``MSG_BATCH`` when tiers == 1 (byte-identical to the single-tier
    implementation) and grouped ``MSG_TBATCH`` otherwise. ``up_fail_base``
    (e.g. ``addr.{gen}`` for a rank-0 parent, ``addr.{gen}.t2.0`` for a
    tier parent) enables failover-key probing on upstream loss."""

    def __init__(self, up_host: str, up_port: int, secret: str,
                 leader_rank: int, host: str = "127.0.0.1",
                 tier: int = 1, index: int = 0, tiers: int = 1,
                 up_fail_base: Optional[str] = None):
        self._up_addr = (up_host, up_port)
        self._secret = secret
        self._leader = leader_rank
        self._tier = tier
        self._index = index
        self._tiers = tiers
        self._tierwire = tiers >= 2
        self._up_fail_base = up_fail_base
        self._up_fo = 0
        self._stop = threading.Event()
        self._jitter = _env_float("HOROVOD_RECONNECT_JITTER", 0.0)
        self._hb_interval = _env_float("HOROVOD_HEARTBEAT_INTERVAL", 5.0)
        linger = _env_float("HOROVOD_HIERARCHY_LINGER_MS", 5.0) / 1000.0
        # entries shipped upstream and not yet answered: the reconnect path
        # re-sends them all (idempotent via the coordinator replay caches)
        self._inflight: Dict[Tuple[int, int], bytes] = {}
        self._inflight_lock = threading.Lock()
        self._vouch: Dict[int, wire.Runs] = {}   # child -> vouched runs
        self._child_conns: Dict[int, Tuple[socket.socket,
                                           threading.Lock]] = {}
        if tier >= 2:
            self.agg = None
            self.gagg = GroupAggregator(self._gship, linger_s=linger)
        else:
            self.agg = HostAggregator(self._ship, linger_s=linger)
            self.gagg = None
        self._bseq = 0
        self._up_send_lock = threading.Lock()
        # fenced leadership: track the highest fencing epoch seen on the
        # upstream stream and reject frames from deposed coordinators
        # (runtime/lease.py; epoch 0 = lease off, wire unchanged)
        self._guard = wire.FenceGuard(rank=leader_rank)
        self._up = self._dial_upstream(MSG_HELLO)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._accept_loop, name="hvd_sub_accept",
                         daemon=True).start()
        threading.Thread(target=self._recv_loop, name="hvd_sub_upstream",
                         daemon=True).start()
        if self._hb_interval > 0:
            threading.Thread(target=self._hb_loop, name="hvd_sub_hb",
                             daemon=True).start()
        if self.gagg is not None:
            # mid tiers have no blocked submitters polling the linger
            # clock, so a ticker drives partial-batch flushes
            threading.Thread(target=self._tick_loop, name="hvd_sub_tick",
                             daemon=True).start()

    # --------------------------------------------------------------- upstream
    def _dial_upstream(self, hello_type: int) -> socket.socket:
        sock = socket.create_connection(self._up_addr, timeout=5)
        sock.settimeout(0.5)
        payload = (wire.encode_resume(-1) if hello_type == MSG_RESUME
                   else b"")
        wire.send_frame(sock, self._secret, hello_type, 0, self._leader,
                        payload, fence=self._guard.epoch)
        return sock

    def _next_bseq(self) -> int:
        with self._inflight_lock:
            self._bseq += 1
            return self._bseq

    def _ship(self, entries: List[Entry]) -> None:
        """HostAggregator flush hook: record the entries as in flight, then
        send one batch frame. Send errors are swallowed — the upstream recv
        loop owns reconnect, and reconnect re-ships the inflight ledger."""
        with self._inflight_lock:
            for r, s, p in entries:
                self._inflight[(r, s)] = p
        self._send_batch(entries)

    def _send_batch(self, entries: List[Entry]) -> None:
        if self._tierwire:
            self._send_groups(coalesce_entries(entries))
            return
        payload = wire.encode_batched_entries(entries)
        try:
            with self._up_send_lock:
                wire.send_frame(self._up, self._secret, MSG_BATCH,
                                self._next_bseq(), self._leader, payload,
                                fence=self._guard.epoch)
        except (ConnectionError, OSError):
            pass

    def _gship(self, groups: List[Group]) -> None:
        """GroupAggregator flush hook (mid tiers): the group ledger lives
        inside the aggregator, so this only frames and sends."""
        self._send_groups(groups)

    def _send_groups(self, groups: List[Group]) -> None:
        payload = wire.encode_tier_batch(self._tier, self._index, groups)
        try:
            with self._up_send_lock:
                wire.send_frame(self._up, self._secret, MSG_TBATCH,
                                self._next_bseq(), self._leader, payload,
                                fence=self._guard.epoch)
        except (ConnectionError, OSError):
            pass

    def _forward(self, mt: int, rank: int, payload: bytes) -> None:
        """Fire-and-forget relay of telemetry/BYE frames, rank preserved."""
        try:
            with self._up_send_lock:
                wire.send_frame(self._up, self._secret, mt, 0, rank, payload,
                                fence=self._guard.epoch)
        except (ConnectionError, OSError):
            pass

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                mt, _, _, payload = wire.recv_frame(self._up, self._secret,
                                                    self._stop,
                                                    guard=self._guard)
                if mt == MSG_FENCED:
                    # the upstream coordinator lost its leadership lease:
                    # treat like a dead upstream — reconnect probes the
                    # failover keys for the new leader
                    raise ConnectionError(
                        "upstream coordinator fenced (%s)"
                        % (payload.decode("utf-8", "replace")
                           or "lost leadership lease"))
            except ShutdownError:
                return
            except (ConnectionError, OSError) as exc:
                if self._stop.is_set():
                    return
                if not self._reconnect_upstream(exc):
                    logger.warning(
                        "sub-coordinator (tier %d, leader rank %d): "
                        "upstream stayed unreachable; releasing children",
                        self._tier, self._leader)
                    self._close_down()
                    return
                continue
            if mt == MSG_BATCH_RESP:
                for rank, seq, data in wire.decode_batched_entries(payload):
                    if self.gagg is not None:
                        self.gagg.deliver_entry(rank, seq, data)
                        continue
                    with self._inflight_lock:
                        self._inflight.pop((rank, seq), None)
                    self.agg.deliver(rank, seq, data)
            elif mt == MSG_TBATCH_RESP:
                rgroups = wire.decode_tier_batch_resp(payload)
                if self.gagg is not None:
                    self.gagg.deliver_groups(rgroups)
                else:
                    for seq, data, runs in rgroups:
                        for rank in wire.runs_to_ranks(runs):
                            with self._inflight_lock:
                                self._inflight.pop((rank, seq), None)
                            self.agg.deliver(rank, seq, data)
            elif mt == MSG_BYE:
                self._close_down()
                return
            # anything else on the upstream socket is ignored: the batch
            # protocol owns this connection

    def _close_down(self) -> None:
        """Release local submitters and cascade shutdown to tier children."""
        if self.agg is not None:
            self.agg.close()
        if self.gagg is not None:
            self.gagg.close()
        for child, (conn, lock) in list(self._child_conns.items()):
            try:
                with lock:
                    wire.send_frame(conn, self._secret, MSG_BYE, 0, 0, b"")
            except (ConnectionError, OSError):
                pass

    def _probe_up_failover(self) -> None:
        """Satellite of the per-tier failover design: on upstream loss, ask
        the KV store whether a standby took over the parent slot
        (``{up_fail_base}.f{n}``) and re-home there."""
        if not self._up_fail_base:
            return
        key = "%s.f%d" % (self._up_fail_base, self._up_fo + 1)
        try:
            addr, secret = _resolve_key(key, timeout=0.3)
        except Exception:
            return
        self._up_fo += 1
        host, _, port = addr.rpartition(":")
        self._up_addr = (host, int(port))
        if secret:
            self._secret = secret
        _blackbox.record(
            _blackbox.K_FAILOVER, "tier_%d" % self._tier,
            "sub-coordinator tier %d index %d re-homing upstream to %s "
            "(failover %d)" % (self._tier, self._index, addr, self._up_fo),
            rank=self._leader)
        logger.warning(
            "sub-coordinator (tier %d index %d, leader rank %d): upstream "
            "failover %d -> %s", self._tier, self._index, self._leader,
            self._up_fo, addr)

    def _reconnect_upstream(self, why: Exception) -> bool:
        for attempt in range(1, 9):
            delay = _backoff_schedule(self._leader, attempt, 0.05, 2.0,
                                      self._jitter)
            if self._stop.wait(delay):
                return False
            if attempt >= 2:
                # same cadence as the flat worker: give the original
                # address one clean retry before chasing failover keys
                self._probe_up_failover()
            try:
                sock = self._dial_upstream(MSG_RESUME)
            except (ConnectionError, OSError):
                continue
            with self._up_send_lock:
                old, self._up = self._up, sock
            try:
                old.close()
            except OSError:
                pass
            if self.gagg is not None:
                groups = self.gagg.inflight_merged()
                nship = wire.runs_count(
                    [r for g in groups for r in g[2]])
                if groups:
                    self._send_groups(groups)
            else:
                with self._inflight_lock:
                    entries = [(r, s, p) for (r, s), p
                               in sorted(self._inflight.items())]
                nship = len(entries)
                if entries:
                    self._send_batch(entries)
            _blackbox.record(
                _blackbox.K_RECONNECT, "tier_%d" % self._tier,
                "sub-coordinator tier %d index %d reconnected upstream "
                "after %s (attempt %d)" % (self._tier, self._index, why,
                                           attempt),
                rank=self._leader)
            logger.warning(
                "sub-coordinator (tier %d index %d, leader rank %d): "
                "reconnected upstream after %s (attempt %d, re-shipped %d "
                "inflight)", self._tier, self._index, self._leader, why,
                attempt, nship)
            return True
        return False

    def _vouched_runs(self) -> wire.Runs:
        """This subtree's live ranks: local leaf connections plus every
        child aggregator's latest vouch, as one merged run list."""
        runs: wire.Runs = []
        if self.agg is not None:
            runs = wire.ranks_to_runs(self.agg.ranks())
        with self._inflight_lock:
            vouches = list(self._vouch.values())
        for v in vouches:
            runs = wire.merge_runs(runs, v)
        return runs

    def _hb_loop(self) -> None:
        while not self._stop.wait(self._hb_interval):
            if self._tierwire:
                runs = self._vouched_runs()
                if not runs:
                    continue
                payload = wire.encode_tier_heartbeat(self._tier,
                                                     self._index, runs)
                mt = MSG_THB
            else:
                alive = self.agg.ranks()
                if not alive:
                    continue
                payload = wire.encode_batched_heartbeat(alive)
                mt = MSG_BATCH_HB
            try:
                with self._up_send_lock:
                    wire.send_frame(self._up, self._secret, mt, 0,
                                    self._leader, payload,
                                    fence=self._guard.epoch)
            except (ConnectionError, OSError):
                pass  # recv loop owns reconnect

    def _tick_loop(self) -> None:
        """Mid-tier linger clock: flush a partial group batch when no
        further child deposit arrives to trigger it."""
        interval = max(0.001, self.gagg._linger_s / 2.0)
        while not self._stop.wait(interval):
            try:
                self.gagg.maybe_flush()
            except Exception:
                pass

    # ------------------------------------------------------------- downstream
    def _accept_loop(self) -> None:
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(0.5)
            threading.Thread(target=self._serve, args=(conn,),
                             name="hvd_sub_conn", daemon=True).start()

    def _serve(self, conn) -> None:
        rank = -1
        try:
            mt, _, rank, _ = wire.recv_frame(conn, self._secret, self._stop)
            if mt not in (MSG_HELLO, MSG_RESUME):
                raise ConnectionError(
                    f"sub-coordinator expected HELLO/RESUME, got {mt}")
            # a RESUME needs no upstream replay here: the worker (or child
            # aggregator) re-sends its in-flight frames itself
            if self.gagg is not None:
                self._serve_child_aggregator(conn, rank)
                return
            self.agg.register(rank)
            while True:
                mt, seq, rank, payload = wire.recv_frame(conn, self._secret,
                                                         self._stop)
                if mt == MSG_BYE:
                    # global shutdown: rank 0 sets bye and tears this
                    # host's upstream down; locals see shutdown responses
                    self._forward(MSG_BYE, rank, b"")
                    return
                if mt == MSG_HEARTBEAT:
                    # local liveness is the open connection itself; the
                    # periodic batch heartbeat vouches for it upstream
                    continue
                if mt in (MSG_METRICS, MSG_TRACE, MSG_BLACKBOX):
                    self._forward(mt, rank, payload)
                    continue
                if mt != MSG_LIST:
                    # DATA/CLOCK bypass the hierarchy on direct rank-0
                    # connections; seeing one here is a protocol bug
                    raise ConnectionError(
                        f"sub-coordinator: unexpected message type {mt}")
                data = self.agg.submit(rank, seq, payload)
                wire.send_frame(conn, self._secret, MSG_RESP, seq, 0, data)
        except ShutdownError:
            pass
        except (ConnectionError, OSError):
            pass
        finally:
            if rank >= 0 and self.agg is not None:
                self.agg.unregister(rank)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_child_aggregator(self, conn, child: int) -> None:
        """Mid-tier downstream: the child is itself an aggregator speaking
        grouped frames; replies route back asynchronously (responses need
        no 1:1 frame pairing, exactly like the host tier's downstream)."""
        lock = threading.Lock()

        def reply_fn(groups: List[Group], entries: List[Entry]) -> None:
            try:
                if groups:
                    with lock:
                        wire.send_frame(conn, self._secret, MSG_TBATCH_RESP,
                                        0, 0,
                                        wire.encode_tier_batch_resp(groups))
                if entries:
                    with lock:
                        wire.send_frame(conn, self._secret, MSG_BATCH_RESP,
                                        0, 0,
                                        wire.encode_batched_entries(entries))
            except (ConnectionError, OSError):
                pass  # child reconnects and re-ships; upstream replay dedupes

        self.gagg.register(child, reply_fn)
        self._child_conns[child] = (conn, lock)
        try:
            while True:
                mt, seq, rank, payload = wire.recv_frame(conn, self._secret,
                                                         self._stop)
                if mt == MSG_BYE:
                    self._forward(MSG_BYE, rank, b"")
                    return
                if mt == MSG_THB:
                    _, _, runs = wire.decode_tier_heartbeat(payload)
                    with self._inflight_lock:
                        self._vouch[child] = runs
                    continue
                if mt in (MSG_METRICS, MSG_TRACE, MSG_BLACKBOX):
                    self._forward(mt, rank, payload)
                    continue
                if mt != MSG_TBATCH:
                    raise ConnectionError(
                        f"tier aggregator: unexpected message type {mt}")
                _, _, groups = wire.decode_tier_batch(payload)
                self.gagg.deposit(child, groups)
        except ShutdownError:
            pass
        except (ConnectionError, OSError):
            pass
        finally:
            self.gagg.unregister(child)
            self._child_conns.pop(child, None)
            with self._inflight_lock:
                self._vouch.pop(child, None)
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self.agg is not None:
            self.agg.close()
        if self.gagg is not None:
            self.gagg.close()
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            self._up.close()
        except OSError:
            pass


class TierStandby:
    """Warm standby for one mid-tier aggregator slot.

    Mid-tier aggregators are stateless relays: every durable negotiation
    artifact lives below them (each host's in-flight ledger re-ships on
    reconnect) or above them (rank 0's per-subtree replay shards, the
    replicated membership journal). So per-tier failover needs no journal
    shard of its own — this standby watches the primary's TCP liveness
    and, after ``misses`` consecutive failed probes, starts a replacement
    aggregator via ``make_aggregator()`` and publishes
    ``addr.{gen}.t{tier}.{index}.f{n}`` for the orphaned children to find
    from their upstream-reconnect probe."""

    def __init__(self, gen: int, tier: int, index: int, secret: str,
                 make_aggregator: Callable[[], "SubCoordinator"],
                 advertise: str = "127.0.0.1",
                 probe_interval: float = 0.25, misses: int = 3):
        self._gen = gen
        self._tier = tier
        self._index = index
        self._secret = secret
        self._make = make_aggregator
        self._advertise = advertise
        self._interval = probe_interval
        self._misses = misses
        self._stop = threading.Event()
        self.promoted = False
        self.agg: Optional[SubCoordinator] = None
        self._thread = threading.Thread(target=self._run,
                                        name="hvd_tier_standby", daemon=True)

    def start(self) -> "TierStandby":
        self._thread.start()
        return self

    def _run(self) -> None:
        key = "addr.%s.t%d.%d" % (self._gen, self._tier, self._index)
        try:
            addr, _ = _resolve_key(key, timeout=30)
        except Exception:
            return
        host, _, port = addr.rpartition(":")
        misses = 0
        while not self._stop.wait(self._interval):
            try:
                s = socket.create_connection((host, int(port)), timeout=1.0)
                s.close()
                misses = 0
            except OSError:
                misses += 1
                if misses >= self._misses:
                    self._promote()
                    return

    def _promote(self) -> None:
        if self._stop.is_set():
            return
        try:
            self.agg = self._make()
        except Exception:
            logger.exception(
                "tier standby: promotion failed (tier %d index %d)",
                self._tier, self._index)
            return
        self.promoted = True
        _publish_key("addr.%s.t%d.%d.f1" % (self._gen, self._tier,
                                            self._index),
                     "%s:%d" % (self._advertise, self.agg.port),
                     self._secret)
        instruments.coord_failovers().inc()
        _blackbox.record(
            _blackbox.K_FAILOVER, "tier_%d" % self._tier,
            "tier standby promoted replacement aggregator for tier %d "
            "index %d" % (self._tier, self._index))
        logger.warning(
            "tier standby: promoted replacement aggregator for tier %d "
            "index %d (port %d)", self._tier, self._index, self.agg.port)

    def stop(self) -> None:
        self._stop.set()
        if self.agg is not None:
            self.agg.stop()
