"""Deadline-based straggler policy (docs/fault-tolerance.md).

The observability stack already *measures* the straggler problem —
``hvd_straggler_skew_seconds``, hvdprof per-rank skew, the anomaly-watch
repeat-straggler signal — but nothing acts on it: one persistently slow
rank sets the step time for the whole pod. :class:`StragglerPolicy` is the
acting half, hosted by the rank-0 negotiation state machine (elastic
``CoordState``) and by the in-process ``PyController``:

* every completed barrier round feeds per-rank arrival times into
  :meth:`observe_round`; a rank whose lateness exceeds
  ``HOROVOD_STRAGGLER_DEADLINE`` (absolute seconds, or ``Nx`` = N times the
  median lateness of its peers) for ``HOROVOD_STRAGGLER_PATIENCE``
  consecutive rounds is marked **excluded**;
* while excluded, barriers complete over the surviving subgroup (the
  generalization of the Join op's proceed-without-a-rank semantics,
  `controller.cc:202-256`) and the data plane averages over ``1/n_active``;
  the late rank trails, fetching each round's response after the fact, and
  its gradient contributions accumulate into an error-feedback residual
  (elastic/executor.py) so no gradient mass is silently dropped;
* an excluded rank that keeps pace again for ``patience`` consecutive
  rounds is re-admitted (hysteresis: its violation counter restarts from
  zero, so re-exclusion needs a full fresh patience run);
* an excluded rank that falls more than ``HOROVOD_STRAGGLER_MAX_SKIP``
  rounds behind the negotiation frontier is **escalated**: the caller
  declares it lost (``rank_lost``) and, when an elastic driver is
  attached, blacklists its host so a hot spare is promoted at the next
  commit boundary (run/elastic_driver.py).

The policy itself is a pure state machine — no locks, no metrics, no
side effects. Callers drive it under their own negotiation lock and act
on the returned transition events, which keeps all three controllers'
exclusion semantics identical and the whole thing unit-testable without
a cluster.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Set

#: relative mode's noise floor (seconds): with ``Nx`` the violation
#: threshold is ``N * max(median peer lateness, floor)``, so tiny absolute
#: spreads on an idle or 2-rank job (where the peer median is 0 by
#: construction — the fastest rank's lateness is always 0) never exclude
RELATIVE_FLOOR_S = 0.05

DEFAULT_PATIENCE = 3
DEFAULT_MAX_SKIP = 50


def _parse_deadline(raw: str):
    """``"3x"`` -> (None, 3.0) relative; ``"2.5"`` -> (2.5, None) absolute.
    Raises ValueError on garbage so a typo fails loudly at init, not as a
    policy that silently never fires."""
    text = raw.strip().lower()
    if text.endswith("x"):
        mult = float(text[:-1])
        if mult <= 0:
            raise ValueError(
                f"HOROVOD_STRAGGLER_DEADLINE={raw!r}: multiplier must be > 0")
        return None, mult
    abs_s = float(text)
    if abs_s <= 0:
        raise ValueError(
            f"HOROVOD_STRAGGLER_DEADLINE={raw!r}: deadline must be > 0")
    return abs_s, None


class StragglerPolicy:
    """Deadline/patience/hysteresis state machine over barrier arrivals.

    Not thread-safe by design: the owning controller already serializes
    every observation and decision under its negotiation lock.
    """

    def __init__(self, deadline_s: Optional[float],
                 multiplier: Optional[float],
                 patience: int = DEFAULT_PATIENCE,
                 max_skip: int = DEFAULT_MAX_SKIP):
        if (deadline_s is None) == (multiplier is None):
            raise ValueError("exactly one of deadline_s/multiplier required")
        self.deadline_s = deadline_s
        self.multiplier = multiplier
        self.patience = max(1, int(patience))
        self.max_skip = max(1, int(max_skip))
        self.excluded: Set[int] = set()
        # per-rank exclusion episode count, kept across readmits — the
        # chronic_straggler doctor signature's ">= N times" evidence
        self.episodes: Dict[int, int] = {}
        self._violations: Dict[int, int] = {}  # consecutive late rounds
        self._ok_rounds: Dict[int, int] = {}   # consecutive on-time rounds
        self._last_seq: Dict[int, int] = {}    # last barrier seq deposited

    # ------------------------------------------------------------- factory
    @classmethod
    def from_env(cls) -> Optional["StragglerPolicy"]:
        """The policy iff ``HOROVOD_STRAGGLER_DEADLINE`` is set; None keeps
        every control-plane byte identical to a policy-less build (the wire
        pin test's guarantee)."""
        raw = os.environ.get("HOROVOD_STRAGGLER_DEADLINE", "").strip()
        if not raw:
            return None
        deadline_s, multiplier = _parse_deadline(raw)
        return cls(
            deadline_s, multiplier,
            patience=int(float(os.environ.get(
                "HOROVOD_STRAGGLER_PATIENCE", DEFAULT_PATIENCE))),
            max_skip=int(float(os.environ.get(
                "HOROVOD_STRAGGLER_MAX_SKIP", DEFAULT_MAX_SKIP))))

    # ------------------------------------------------------------ plumbing
    def note_deposit(self, rank: int, seq: int) -> None:
        """Record a rank's latest barrier deposit (its negotiation
        frontier); :meth:`on_negotiate` escalates when an excluded rank's
        frontier trails the round being negotiated by more than max_skip."""
        if seq > self._last_seq.get(rank, -1):
            self._last_seq[rank] = seq

    def threshold_for(self, rank: int,
                      lateness: Dict[int, float]) -> float:
        """This round's violation threshold for ``rank``: the absolute
        deadline, or multiplier x median of the OTHER ranks' lateness
        (floored) — median-of-peers so the straggler's own lateness never
        inflates the bar it is judged against."""
        if self.deadline_s is not None:
            return self.deadline_s
        peers = sorted(v for r, v in lateness.items() if r != rank)
        if peers:
            mid = len(peers) // 2
            med = (peers[mid] if len(peers) % 2
                   else (peers[mid - 1] + peers[mid]) / 2.0)
        else:
            med = 0.0
        return self.multiplier * max(med, RELATIVE_FLOOR_S)

    # ------------------------------------------------------------ decisions
    def observe_round(self, arrivals: Dict[int, float]) -> Dict[str, List[int]]:
        """Feed one completed round's per-rank first-arrival times (every
        member present, including currently-excluded ranks that trailed in
        late). Returns the transition events:
        ``{"excluded": [...], "readmitted": [...]}``."""
        events: Dict[str, List[int]] = {"excluded": [], "readmitted": []}
        if len(arrivals) < 2:
            return events
        t0 = min(arrivals.values())
        lateness = {r: t - t0 for r, t in arrivals.items()}
        for rank in sorted(lateness):
            violated = lateness[rank] > self.threshold_for(rank, lateness)
            if rank in self.excluded:
                if violated:
                    self._ok_rounds[rank] = 0
                else:
                    self._ok_rounds[rank] = self._ok_rounds.get(rank, 0) + 1
                    if self._ok_rounds[rank] >= self.patience:
                        self.excluded.discard(rank)
                        # hysteresis: a readmitted rank starts clean — going
                        # back out requires a full fresh patience run
                        self._violations[rank] = 0
                        self._ok_rounds.pop(rank, None)
                        events["readmitted"].append(rank)
            else:
                if not violated:
                    self._violations[rank] = 0
                    continue
                self._violations[rank] = self._violations.get(rank, 0) + 1
                if (self._violations[rank] >= self.patience
                        # never exclude down to an empty subgroup: the round
                        # must keep at least one on-pace participant
                        and len(self.excluded) < len(arrivals) - 1):
                    self.excluded.add(rank)
                    self.episodes[rank] = self.episodes.get(rank, 0) + 1
                    self._ok_rounds[rank] = 0
                    events["excluded"].append(rank)
        return events

    def on_negotiate(self, seq: int,
                     members: Iterable[int]) -> List[int]:
        """Called once per negotiated barrier round. Returns the excluded
        ranks whose deposit frontier now trails ``seq`` by more than
        ``max_skip`` rounds — the caller escalates those to ``rank_lost``
        / hot-spare promotion. Rank 0 is never escalated: it hosts the
        coordinator, so "promote its replacement" has nothing to promote
        onto (parity with the collective-timeout loss path, which also
        refuses to declare rank 0 dead)."""
        mem = set(members)
        self.excluded &= mem
        escalate = []
        for rank in sorted(self.excluded):
            if rank == 0:
                continue
            if seq - self._last_seq.get(rank, seq) > self.max_skip:
                escalate.append(rank)
        for rank in escalate:
            self.forget(rank)
        return escalate

    def forget(self, rank: int) -> None:
        """Drop a rank's runtime state (lost or escalated). Episode counts
        survive on purpose: chronic behavior is the history, not the
        moment."""
        self.excluded.discard(rank)
        self._violations.pop(rank, None)
        self._ok_rounds.pop(rank, None)
        self._last_seq.pop(rank, None)

    def reset(self) -> None:
        """Membership epoch change: every barrier seq realigns and the old
        member set's counters are meaningless. Episode history survives."""
        self.excluded.clear()
        self._violations.clear()
        self._ok_rounds.clear()
        self._last_seq.clear()
