"""Request/Response value types for the collective engine.

Reference parity: `horovod/common/message.{h,cc}` — Request (what one rank wants
done with one named tensor) and Response (what the coordinator decided a tick
should execute, possibly fused over several names). The reference serializes
these with FlatBuffers (`wire/message.fbs`); here the in-process engine passes
them as objects and the cross-process control plane uses the compact binary
codec in :mod:`horovod_tpu.runtime.wire` (C++-owned once the native core lands).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, List, NamedTuple, Optional, Tuple


class RequestType(enum.IntEnum):
    # Parity: message.h:48-49 (ALLREDUCE/ALLGATHER/BROADCAST/JOIN/ADASUM).
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5  # extension (north-star op set)


class ResponseType(enum.IntEnum):
    # Parity: message.h:133-134 (response adds ERROR).
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    ERROR = 6


class Frame(NamedTuple):
    """One decoded control-plane TCP frame (wire.recv_frame). Field order
    matches the wire head so existing tuple-style unpacking keeps working."""

    msg_type: int
    seq: int
    rank: int
    payload: bytes


class AlltoallvResult(NamedTuple):
    """Result of a ragged ``alltoall(tensor, splits)``: the gathered output
    plus the negotiated per-source row counts (later-horovod's
    ``(output, received_splits)`` return shape). Produced by the executor,
    carried through the handle manager; framework surfaces unwrap it."""

    output: Any
    received_splits: Tuple[int, ...]


@dataclass
class Request:
    """One rank's intent for one named tensor (message.h Request)."""

    request_rank: int
    request_type: RequestType
    tensor_name: str
    tensor_dtype: str
    tensor_shape: Tuple[int, ...]
    root_rank: int = -1  # broadcast only
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0


@dataclass
class Response:
    """Coordinator decision for a tick; may cover several fused names
    (controller.cc:626-750 FuseResponses)."""

    response_type: ResponseType
    tensor_names: List[str] = field(default_factory=list)
    error_message: str = ""
    # devices involved; informational (common.h Response devices field)
    devices: List[int] = field(default_factory=list)
    # allgather: per-rank dim0 sizes per tensor (tensor_sizes in reference)
    tensor_sizes: List[List[int]] = field(default_factory=list)
    # allreduce: divide the sum by world size (Average op); the reference does
    # this division in-framework (`tensorflow/__init__.py:117`) — here it fuses
    # into the compiled collective.
    average: bool = False
    prescale: float = 1.0
    postscale: float = 1.0
    root_rank: int = -1
    # Metadata the cross-process plane negotiates so a rank can participate in
    # a collective it has no local entries for (joined ranks contribute zeros,
    # `controller.cc:202-256`) and so ragged allgathers know every rank's dim0
    # (Response::tensor_sizes in the reference):
    tensor_dtype: str = ""
    # per-tensor shape of the rank-0 instance (allgather: dim0 is rank 0's;
    # use tensor_sizes for the negotiated per-rank dim0s)
    tensor_shapes: List[Tuple[int, ...]] = field(default_factory=list)
    # negotiated wire compression for the fused bucket ("" = none, "int8",
    # "int8-dcn"): the coordinator's decision every rank compiles against,
    # so the quantize→collective→dequantize programs match across ranks
    compression: str = ""
    # membership epoch the decision was negotiated under (-1 = non-elastic);
    # executing a response against a different epoch means a rank set change
    # raced this tick, and the executor must fail fast instead of exchanging
    # data with a stale member set (docs/elastic.md)
    epoch: int = -1
    # straggler policy (runtime/straggler.py): ranks whose contribution is
    # ABSENT from this collective — the executor zero-fills their slots, so
    # an averaging engine must divide by world - len(excluded_ranks) instead
    # of world. In-memory only (set by the in-process controllers); the
    # cross-process plane carries exclusion in the ResponseList tail and
    # corrects the average via the data plane's participant count.
    excluded_ranks: Optional[List[int]] = None


@dataclass
class MetricsReport:
    """One rank's metrics-registry snapshot shipped to the coordinator over
    the control channel (``MSG_METRICS`` frames, fire-and-forget). The
    coordinator stores the latest report per rank and the /metrics endpoint
    renders the merge (docs/metrics.md). ``snapshot`` is the plain-dict shape
    produced by :meth:`horovod_tpu.metrics.MetricsRegistry.snapshot`."""

    rank: int
    timestamp: float
    snapshot: dict


@dataclass
class TensorTableEntry:
    """Pending named tensor from one rank (`common.h:129-250` TensorTableEntry).

    ``array`` is a committed jax.Array on the rank's device; ``callback``
    receives (status_ok, result_or_error).
    """

    tensor_name: str
    rank: int
    request_type: RequestType
    array: Any
    root_rank: int = -1
    callback: Optional[Any] = None
    handle: Optional[int] = None
    enqueue_seq: int = 0
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    average: bool = False  # Average op: fused divide-by-size
    # alltoall splits (extension)
    splits: Optional[Any] = None
    # requested wire compression ("" = none; see Response.compression)
    compression: str = ""
    # False = this entry is already a client-built bucket (backward-pass
    # bucket overlap, optim/distributed.py): the controller must not merge
    # it with other tensors — re-fusing hand-made buckets would serialize
    # the wire behind the last bucket and erase the overlap. The flag is
    # rank-local but set deterministically by the same client code on every
    # rank, so enforcement decisions resolve identically everywhere; planes
    # whose wire/ABI cannot carry it (native tick frames, coordinator
    # Requests) are backstopped by the engine's response split.
    fusable: bool = True
