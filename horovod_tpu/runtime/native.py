"""ctypes bindings to the native engine core (libhvd_tpu_core.so).

The reference loads its C++ engine the same way — a ctypes wrapper over a C
ABI (`horovod/common/basics.py:27-31`). The library is built from
`horovod_tpu/_core/` by `make`; if missing, it is built on first use (the
toolchain is part of the supported environment) and the engine falls back to
the pure-Python controller only if compilation is impossible
(``HVD_TPU_NATIVE=0`` forces the fallback).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

from . import wire
from .messages import RequestType, Response, TensorTableEntry

_CORE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "_core")
_LIB_PATH = os.path.join(_CORE_DIR, "libhvd_tpu_core.so")

_lib = None
_lib_lock = threading.Lock()

# numpy dtype name -> DType code (common.h)
_DTYPE_CODES = {
    "float16": 0, "bfloat16": 1, "float32": 2, "float64": 3,
    "int8": 4, "int16": 5, "int32": 6, "int64": 7,
    "uint8": 8, "uint16": 9, "uint32": 10, "uint64": 11, "bool": 12,
}


def dtype_code(dtype) -> int:
    return _DTYPE_CODES.get(str(dtype), 2)


def _build() -> bool:
    try:
        r = subprocess.run(["make", "-C", _CORE_DIR], capture_output=True,
                           timeout=300)
        return r.returncode == 0 and os.path.exists(_LIB_PATH)
    except Exception:
        return False


def load_library():
    """Load (building if needed) the native core; returns None on failure."""
    global _lib
    with _lib_lock:
        if os.environ.get("HVD_TPU_NATIVE", "1") in ("0", "false"):
            return None
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        lib = _load_and_bind()
        if lib is None and _build():
            # a prebuilt .so can predate newly added C entry points (the
            # build products are gitignored); one rebuild-and-retry keeps
            # the returns-None-on-failure contract instead of raising
            lib = _load_and_bind()
        _lib = lib
        return _lib


def _load_and_bind():
    """dlopen + bind every C symbol; None if the library is unloadable or
    missing a symbol (stale build)."""
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    try:
        _bind(lib)
    except AttributeError:
        return None
    return lib


def _bind(lib) -> None:
    lib.hvd_core_create.restype = ctypes.c_int64
    lib.hvd_core_create.argtypes = [
        ctypes.c_int32, ctypes.c_int64, ctypes.c_double, ctypes.c_double,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int32,
        ctypes.c_double, ctypes.c_int32, ctypes.c_int32]
    lib.hvd_core_destroy.argtypes = [ctypes.c_int64]
    lib.hvd_core_submit.restype = ctypes.c_int64
    lib.hvd_core_submit.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.c_int32, ctypes.c_double, ctypes.c_double,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int32]
    lib.hvd_core_join.restype = ctypes.c_int64
    lib.hvd_core_join.argtypes = [ctypes.c_int64, ctypes.c_int32]
    lib.hvd_core_tick.restype = ctypes.c_int64
    lib.hvd_core_tick.argtypes = [ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_char_p)]
    lib.hvd_core_shutdown.restype = ctypes.c_int64
    lib.hvd_core_shutdown.argtypes = [ctypes.c_int64,
                                      ctypes.POINTER(ctypes.c_char_p)]
    for f in ("hvd_core_timeline_op_start", "hvd_core_timeline_activity"):
        getattr(lib, f).argtypes = [ctypes.c_int64, ctypes.c_char_p,
                                    ctypes.c_char_p]
    lib.hvd_core_timeline_op_end.argtypes = [ctypes.c_int64,
                                             ctypes.c_char_p]
    lib.hvd_core_timeline_cycle.argtypes = [ctypes.c_int64]
    lib.hvd_core_timeline_cache.argtypes = [ctypes.c_int64,
                                            ctypes.c_uint64,
                                            ctypes.c_uint64]
    lib.hvd_core_report_score.restype = ctypes.c_int32
    lib.hvd_core_report_score.argtypes = [ctypes.c_int64, ctypes.c_int64,
                                          ctypes.c_double]
    lib.hvd_core_fusion_threshold.restype = ctypes.c_int64
    lib.hvd_core_fusion_threshold.argtypes = [ctypes.c_int64]
    lib.hvd_core_cycle_time_ms.restype = ctypes.c_double
    lib.hvd_core_cycle_time_ms.argtypes = [ctypes.c_int64]
    lib.hvd_core_cache_hits.restype = ctypes.c_uint64
    lib.hvd_core_cache_hits.argtypes = [ctypes.c_int64]
    lib.hvd_core_cache_misses.restype = ctypes.c_uint64
    lib.hvd_core_cache_misses.argtypes = [ctypes.c_int64]
    lib.hvd_tuner_active.restype = ctypes.c_int32
    lib.hvd_tuner_active.argtypes = [ctypes.c_int64]
    lib.hvd_core_autotune_active.restype = ctypes.c_int32
    lib.hvd_core_autotune_active.argtypes = [ctypes.c_int64]
    lib.hvd_tuner_create.restype = ctypes.c_int64
    lib.hvd_tuner_create.argtypes = [ctypes.c_int64, ctypes.c_double,
                                     ctypes.c_uint64]
    lib.hvd_tuner_configure.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_double]
    lib.hvd_core_tuner_configure.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_double]
    lib.hvd_tuner_update.restype = ctypes.c_int32
    lib.hvd_tuner_update.argtypes = [ctypes.c_int64, ctypes.c_int64,
                                     ctypes.c_double]
    lib.hvd_tuner_threshold.restype = ctypes.c_int64
    lib.hvd_tuner_threshold.argtypes = [ctypes.c_int64]
    lib.hvd_tuner_cycle_ms.restype = ctypes.c_double
    lib.hvd_tuner_cycle_ms.argtypes = [ctypes.c_int64]
    lib.hvd_tuner_destroy.argtypes = [ctypes.c_int64]


def autotune_env_knobs():
    """Parse the reference's four HOROVOD_AUTOTUNE_* tuning knobs
    (`horovod/common/parameter_manager.cc:42-59`): warmup samples,
    steps per sample, Bayes-opt max samples, GP noise. Unset/invalid maps
    to the sentinel (-1 / -1.0) the native ``Configure()`` treats as
    keep-default (warmup accepts an explicit 0)."""
    def _int(name: str) -> int:
        v = os.environ.get(name, "")
        try:
            return int(v) if v else -1
        except ValueError:
            return -1

    def _flt(name: str) -> float:
        v = os.environ.get(name, "")
        try:
            return float(v) if v else -1.0
        except ValueError:
            return -1.0

    return (_int("HOROVOD_AUTOTUNE_WARMUP_SAMPLES"),
            _int("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"),
            _int("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"),
            _flt("HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"))


class NativeTuner:
    """Standalone GP/EI parameter manager (autotune.cc) for the cross-process
    coordinator: rank 0 feeds aggregated throughput scores and reads back the
    tuned (fusion_threshold, cycle_time) to broadcast in its ResponseList —
    the coordinated analogue of the in-process autotune path. Raises if the
    native core cannot be loaded (coordinated autotune is native-only; the
    caller degrades to no-tuning with a warning)."""

    def __init__(self, fusion_threshold: int, cycle_time_ms: float,
                 seed: int = 0, knobs=None):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native core unavailable")
        self._lib = lib
        self._h = lib.hvd_tuner_create(fusion_threshold, cycle_time_ms, seed)
        # the four HOROVOD_AUTOTUNE_* sub-knobs (env unless given explicitly)
        w, s, m, n = knobs if knobs is not None else autotune_env_knobs()
        lib.hvd_tuner_configure(self._h, w, s, m, n)

    def update(self, nbytes: int, seconds: float) -> bool:
        """Record one scored interval; True if tuned params changed."""
        return bool(self._lib.hvd_tuner_update(self._h, nbytes, seconds))

    def active(self) -> bool:
        """True while the GP is still exploring (False once settled)."""
        return bool(self._lib.hvd_tuner_active(self._h))

    def fusion_threshold(self) -> int:
        return self._lib.hvd_tuner_threshold(self._h)

    def cycle_time_ms(self) -> float:
        return self._lib.hvd_tuner_cycle_ms(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.hvd_tuner_destroy(self._h)
            self._h = 0


class NativeController:
    """Thin stateful wrapper over one native engine instance.

    Interface consumed by runtime.engine.Engine: submit/join/tick/shutdown +
    timeline hooks + autotune scoring. Tensor data never crosses this
    boundary — only metadata and handles.
    """

    SUBMIT_DUPLICATE = -1
    SUBMIT_SHUTDOWN = -2

    def __init__(self, world: int, fusion_threshold: int,
                 stall_warning_s: float, stall_shutdown_s: float,
                 cache_capacity: int, fusion_enabled: bool,
                 timeline_path: Optional[str], autotune: bool,
                 cycle_time_ms: float, local_only: bool = False,
                 self_rank: int = 0):
        self._lib = load_library()
        if self._lib is None:
            raise RuntimeError("native core unavailable")
        self._eng = self._lib.hvd_core_create(
            world, fusion_threshold, stall_warning_s, stall_shutdown_s,
            cache_capacity, int(fusion_enabled),
            timeline_path.encode() if timeline_path else None,
            int(autotune), cycle_time_ms, int(local_only), self_rank)
        self._dead = False
        if autotune:
            self._lib.hvd_core_tuner_configure(self._eng,
                                               *autotune_env_knobs())

    def submit(self, entry: TensorTableEntry) -> int:
        shape = np.asarray(entry.array.shape, dtype=np.int64)
        dims = shape.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)) \
            if shape.size else ctypes.POINTER(ctypes.c_int64)()
        nil = ctypes.POINTER(ctypes.c_int64)()
        if entry.splits is not None:
            sp = np.asarray(entry.splits, dtype=np.int64)
            spp = sp.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)) \
                if sp.size else nil
            nsp = int(sp.size)
        else:
            spp, nsp = nil, 0
        return self._lib.hvd_core_submit(
            self._eng, entry.tensor_name.encode(), entry.rank,
            int(entry.request_type), dtype_code(entry.array.dtype),
            len(entry.array.shape), dims, entry.root_rank,
            int(entry.average), entry.prescale_factor, entry.postscale_factor,
            spp, nsp)

    def join(self, rank: int) -> int:
        return self._lib.hvd_core_join(self._eng, rank)

    def tick(self):
        p = ctypes.c_char_p()
        n = self._lib.hvd_core_tick(self._eng, ctypes.byref(p))
        if n <= 0:
            return None
        buf = ctypes.string_at(p, n)
        return wire.decode_tick(buf)

    def shutdown(self) -> List[int]:
        if self._dead:
            return []
        self._dead = True
        p = ctypes.c_char_p()
        n = self._lib.hvd_core_shutdown(self._eng, ctypes.byref(p))
        orphans = wire.decode_handle_list(ctypes.string_at(p, n)) if n > 0 else []
        self._lib.hvd_core_destroy(self._eng)
        return orphans

    # ---- timeline / autotune
    def timeline_op_start(self, tensor: str, op: str) -> None:
        self._lib.hvd_core_timeline_op_start(self._eng, tensor.encode(),
                                             op.encode())

    def timeline_activity(self, tensor: str, activity: str) -> None:
        self._lib.hvd_core_timeline_activity(self._eng, tensor.encode(),
                                             activity.encode())

    def timeline_op_end(self, tensor: str) -> None:
        self._lib.hvd_core_timeline_op_end(self._eng, tensor.encode())

    def timeline_cycle(self) -> None:
        self._lib.hvd_core_timeline_cycle(self._eng)

    def timeline_cache(self, hits: int, misses: int) -> None:
        self._lib.hvd_core_timeline_cache(self._eng, hits, misses)

    def report_score(self, nbytes: int, seconds: float) -> bool:
        return bool(self._lib.hvd_core_report_score(self._eng, nbytes,
                                                    seconds))

    def autotune_active(self) -> bool:
        return bool(self._lib.hvd_core_autotune_active(self._eng))

    def fusion_threshold(self) -> int:
        return self._lib.hvd_core_fusion_threshold(self._eng)

    def cycle_time_ms(self) -> float:
        return self._lib.hvd_core_cycle_time_ms(self._eng)

    def cache_stats(self) -> Tuple[int, int]:
        return (self._lib.hvd_core_cache_hits(self._eng),
                self._lib.hvd_core_cache_misses(self._eng))

    def excluded_ranks(self) -> frozenset:
        """The C++ core predates the straggler policy and never excludes a
        rank — the "absent ⇒ full participation" agreement across
        controllers (runtime/straggler.py)."""
        return frozenset()
