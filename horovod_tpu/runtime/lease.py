"""Lease-based fenced coordinator leadership (docs/fault-tolerance.md).

The crash-only failover from the warm-standby plane cannot distinguish a
*dead* rank 0 from a *partitioned* one: both look like a lost replication
stream, and promoting on the latter yields two live coordinators. This
module closes that hole with a TTL lease in the rendezvous KV:

* The active coordinator holds ``lease.{gen}`` (value
  ``"{fence_epoch}:{owner_rank}:{renewal_count}"``) and compare-and-swap
  renews it every ``HOROVOD_LEASE_RENEW`` seconds, bumping the count.
* A holder that cannot renew **self-fences** — stops serving and parks its
  exchange — once ``FENCE_FRACTION * TTL`` has elapsed since its last
  successful renewal, strictly before the TTL.
* A standby promotes only by *acquiring* the lease: it requires the value
  to sit unchanged for a full TTL measured on its **own monotonic clock**
  (observed stasis — no cross-host clock comparison anywhere), then CAS-es
  in ``epoch+1`` with itself as owner. The CAS means exactly one of any
  number of racing acquirers wins.

TTL arithmetic: the holder fences at ``last_renewal + FENCE_FRACTION*TTL``
on its clock; an acquirer moves at ``last_observed_change + TTL`` on its
clock, and the observed change happened *after* the holder's renewal was
written. With FENCE_FRACTION < 1 the fence strictly precedes any takeover,
so no instant has two serving coordinators — the invariant the jepsen-lite
checker (`faultinject/jepsen.py`) replays blackbox logs to verify.

The lease is explicitly opt-in (``HOROVOD_LEASE_TTL`` set) and requires the
launcher KV (``HVD_KV_ADDR``): the jax.distributed fallback KV has no CAS.
With the knob unset nothing here runs and the wire stays byte-identical to
the pre-fencing format (fencing epoch 0 is never stamped).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional, Tuple

from .. import blackbox as _blackbox
from .. import faultinject
from ..metrics import instruments

logger = logging.getLogger("horovod_tpu")

LEASE_SCOPE = "hvdcoord"

# A holder self-fences this fraction of the TTL after its last successful
# renewal — strictly before any acquirer (who waits a full TTL) can move.
FENCE_FRACTION = 0.75


def lease_enabled() -> bool:
    return bool(os.environ.get("HOROVOD_LEASE_TTL")) and bool(
        os.environ.get("HVD_KV_ADDR"))


def lease_ttl() -> float:
    v = os.environ.get("HOROVOD_LEASE_TTL")
    return float(v) if v else 10.0


def lease_renew_interval() -> float:
    v = os.environ.get("HOROVOD_LEASE_RENEW")
    return float(v) if v else lease_ttl() / 4.0


def _parse_value(raw: Optional[bytes]) -> Optional[Tuple[int, int, int]]:
    """(fence_epoch, owner_rank, renewal_count), or None for absent/garbage."""
    if raw is None:
        return None
    try:
        epoch, owner, count = raw.decode().split(":")
        return int(epoch), int(owner), int(count)
    except (ValueError, UnicodeDecodeError):
        return None


def read_lease_epoch(gen: int, key: Optional[str] = None) -> int:
    """Best-effort read of the current fencing epoch — used by workers on
    failover probes to seed their FenceGuard. 0 when no lease exists.
    ``key`` overrides the coordinator default ``lease.{gen}`` (the serving
    plane holds its own lease under ``serve.lease.{gen}``)."""
    kv_addr = os.environ.get("HVD_KV_ADDR")
    if not kv_addr:
        return 0
    try:
        from ..run.rendezvous import KVStoreClient

        client = KVStoreClient(kv_addr, os.environ.get("HVD_SECRET", ""),
                               timeout=2.0)
        parsed = _parse_value(
            client.get(LEASE_SCOPE, key or f"lease.{gen}"))
        return parsed[0] if parsed else 0
    except (ConnectionError, OSError):
        return 0


class LeaseManager:
    """One rank's handle on the leadership lease for one init generation.

    Holder side: :meth:`acquire_initial` / :meth:`acquire_over` +
    :meth:`start_renewing`. Acquirer side: :meth:`read` polled by the
    standby's lease watcher, which calls :meth:`acquire_over` once it has
    observed a full TTL of stasis.
    """

    def __init__(self, gen: int, rank: int, key: Optional[str] = None):
        from ..run.rendezvous import KVStoreClient

        # The default key fences training-coordinator leadership; other
        # planes (the serving frontend) pass their own key so the two
        # leaderships are independent leases with independent epochs.
        self._key = key or f"lease.{gen}"
        self._rank = rank
        self._client = KVStoreClient(
            os.environ["HVD_KV_ADDR"], os.environ.get("HVD_SECRET", ""),
            timeout=2.0)
        self.ttl = lease_ttl()
        self.renew_interval = min(lease_renew_interval(), self.ttl / 2.0)
        self._epoch = 0
        self._count = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def epoch(self) -> int:
        return self._epoch

    def _check_partition(self) -> None:
        part = faultinject.partition_for_rank(self._rank)
        if part is not None and part.blocks_kv(self._rank):
            raise ConnectionError(
                "faultinject: rendezvous KV unreachable from rank %d "
                "(network partition)" % self._rank)

    def _value(self) -> bytes:
        return f"{self._epoch}:{self._rank}:{self._count}".encode()

    def read(self) -> Optional[bytes]:
        """Raw lease value (None = absent). Raises ConnectionError when the
        KV is unreachable — the caller must NOT treat that as stasis."""
        self._check_partition()
        return self._client.get(LEASE_SCOPE, self._key)

    def acquire_initial(self) -> int:
        """Rank 0 at startup: take epoch 1 via an absent-CAS. A leftover
        value (coordinator restart inside one generation) is superseded by
        CAS-ing epoch+1 over whatever is there."""
        self._check_partition()
        self._epoch, self._count = 1, 0
        if self._client.put_if(LEASE_SCOPE, self._key, self._value(), None):
            self._record("lease_acquired epoch=%d" % self._epoch)
            return self._epoch
        for _ in range(3):
            cur = self._client.get(LEASE_SCOPE, self._key)
            parsed = _parse_value(cur)
            self._epoch = (parsed[0] + 1) if parsed else 1
            self._count = 0
            if self._client.put_if(LEASE_SCOPE, self._key, self._value(),
                                   cur):
                self._record("lease_acquired epoch=%d" % self._epoch)
                return self._epoch
        raise ConnectionError(
            "could not acquire the leadership lease %s: the key kept "
            "moving under CAS (another live coordinator?)" % self._key)

    def acquire_over(self, observed: Optional[bytes]) -> Optional[int]:
        """Standby takeover: CAS ``observed`` (the stale value it watched
        for a full TTL) to epoch+1 owned by this rank. None = lost the race
        to another acquirer or a revived holder; raises on KV loss."""
        self._check_partition()
        parsed = _parse_value(observed)
        new_epoch = (parsed[0] + 1) if parsed else 1
        old_epoch, old_count = self._epoch, self._count
        self._epoch, self._count = new_epoch, 0
        if self._client.put_if(LEASE_SCOPE, self._key, self._value(),
                               observed):
            self._record("lease_acquired epoch=%d" % new_epoch)
            return new_epoch
        self._epoch, self._count = old_epoch, old_count
        return None

    def start_renewing(self, on_fence: Callable[[str], None]) -> None:
        """Run the holder's renewal loop on a daemon thread. ``on_fence`` is
        invoked exactly once — from the renewal thread — if the lease is
        lost (CAS superseded) or unrenewable past the fence deadline."""
        self._thread = threading.Thread(
            target=self._renew_loop, args=(on_fence,),
            name="hvd_lease_renew", daemon=True)
        self._thread.start()

    def _renew_loop(self, on_fence: Callable[[str], None]) -> None:
        last_ok = time.monotonic()
        fence_after = self.ttl * FENCE_FRACTION
        while not self._stop.wait(self.renew_interval):
            try:
                # the KV client rides a plain socket, not the wrapped
                # control plane: the partition cut must be asked explicitly
                # or an injected outage would never reach the renewal path
                self._check_partition()
                expected = self._value()
                self._count += 1
                if self._client.put_if(LEASE_SCOPE, self._key, self._value(),
                                       expected):
                    last_ok = time.monotonic()
                    instruments.lease_renewals().inc()
                    self._record("lease_renewed epoch=%d count=%d"
                                 % (self._epoch, self._count))
                    continue
                # CAS mismatch: somebody else moved the lease — this
                # coordinator is deposed, fence NOW regardless of deadline
                self._count -= 1
                self._record("self_fenced epoch=%d reason=deposed"
                             % self._epoch)
                on_fence("leadership lease %s superseded (deposed)"
                         % self._key)
                return
            except (ConnectionError, OSError) as exc:
                self._count -= 1
                logger.warning(
                    "lease: renewal of %s failed (%s); fencing in %.1fs "
                    "unless the KV comes back", self._key, exc,
                    max(0.0, fence_after - (time.monotonic() - last_ok)))
            if time.monotonic() - last_ok >= fence_after:
                self._record("self_fenced epoch=%d reason=renewal_timeout"
                             % self._epoch)
                on_fence(
                    "could not renew leadership lease %s for %.1fs "
                    "(%.0f%% of the %.1fs TTL)"
                    % (self._key, time.monotonic() - last_ok,
                       FENCE_FRACTION * 100, self.ttl))
                return

    def _record(self, detail: str) -> None:
        _blackbox.record(_blackbox.K_FENCE, "rank_%d" % self._rank, detail,
                         rank=self._rank)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
