# Copyright 2018 Uber Technologies, Inc. All Rights Reserved.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or
# implied. See the License for the specific language governing
# permissions and limitations under the License.
# ==============================================================================
"""Monotonic trace clock with cross-rank offset alignment.

All trace timestamps are microseconds in a timebase anchored once per
process: wall-clock at import plus a ``time.perf_counter_ns`` delta.
Because the delta is monotonic, timestamps can never go backwards even
if the system wall clock steps (NTP slew, manual adjustment) — the wall
origin only fixes the epoch so traces from different processes land in
the same ballpark before offset correction.

``trace_us()`` additionally applies the rank-0 offset learned by the
NTP-style handshake in :func:`compute_offset_us`, so spans recorded on
different hosts align on a shared timeline.
"""

import time

# Anchored once at import; everything after is pure perf_counter deltas.
_PERF_ORIGIN_NS = time.perf_counter_ns()
_WALL_ORIGIN_US = int(time.time() * 1e6)

# Offset (us) added to local_us() to land on rank 0's timeline.
_offset_us = 0


def local_us() -> int:
    """Monotonic microseconds in this process's local timebase."""
    return _WALL_ORIGIN_US + (time.perf_counter_ns() - _PERF_ORIGIN_NS) // 1000


def trace_us() -> int:
    """Monotonic microseconds aligned to rank 0's timeline."""
    return local_us() + _offset_us


def offset_us() -> int:
    return _offset_us


def set_offset_us(offset: int) -> None:
    global _offset_us
    _offset_us = int(offset)


def reset() -> None:
    """Drop any learned offset (tests / re-init)."""
    set_offset_us(0)


def compute_offset_us(samples) -> int:
    """Pick the clock offset from ``(t0, server_us, t1)`` probe samples.

    Classic NTP estimate: for each round trip, assume the server stamped
    its reply halfway through, so ``offset = server - (t0 + t1) / 2``.
    The sample with the smallest round-trip time carries the least queuing
    noise, so its offset estimate wins.
    """
    best_rtt = None
    best_off = 0
    for t0, server_us, t1 in samples:
        rtt = t1 - t0
        if rtt < 0:
            continue
        if best_rtt is None or rtt < best_rtt:
            best_rtt = rtt
            best_off = server_us - (t0 + t1) // 2
    return int(best_off)


def sync_offset(probe, rounds: int = 5) -> int:
    """Run ``rounds`` probes against rank 0 and install the best offset.

    ``probe`` is a callable taking the local send timestamp (us) and
    returning the server's ``trace_us`` at reply time. Returns the
    installed offset.
    """
    samples = []
    for _ in range(max(1, rounds)):
        t0 = local_us()
        server_us = probe(t0)
        t1 = local_us()
        samples.append((t0, server_us, t1))
    off = compute_offset_us(samples)
    set_offset_us(off)
    return off
