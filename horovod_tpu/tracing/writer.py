# Copyright 2018 Uber Technologies, Inc. All Rights Reserved.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or
# implied. See the License for the specific language governing
# permissions and limitations under the License.
# ==============================================================================
"""Chrome-trace output: the streaming event writer and the merged-trace
serializer.

``ChromeTraceWriter`` is the queue-fed writer thread previously embedded
in :class:`horovod_tpu.utils.timeline.Timeline`; the timeline now holds
one of these as a thin adapter. ``write_merged`` turns a batch of
cross-rank spans into one strictly-valid Chrome/Perfetto JSON object —
valid by construction because it is a single ``json.dump``.
"""

import json
import queue
import threading

from . import spans as S

# Event names shared with the analyzer.
EV_NEGOTIATE = "NEGOTIATE"
EV_WIRE = "WIRE"
EV_DEQUEUE = "DEQUEUE"
EV_WAIT = "WAIT"
EV_STEP = "STEP"


class ChromeTraceWriter:
    """Streaming Chrome-trace array writer fed through a queue.

    Keeps the file one valid JSON array at all times once :meth:`close`
    appends ``]`` (comma before every event after the first); batches the
    flush to queue-empty boundaries to keep the hot path off the disk.
    """

    def __init__(self, path):
        self._q = queue.Queue()
        self._wrote_event = False
        self._f = open(path, "w")
        self._f.write("[\n")
        self._thread = threading.Thread(
            target=self._loop, name="hvd_tpu_trace_writer", daemon=True)
        self._thread.start()

    def emit(self, ev: dict) -> None:
        self._q.put(ev)

    def _loop(self) -> None:
        while True:
            ev = self._q.get()
            if ev is None:
                return
            while True:
                if self._wrote_event:
                    self._f.write(",\n")
                self._f.write(json.dumps(ev))
                self._wrote_event = True
                try:
                    ev = self._q.get_nowait()
                except queue.Empty:
                    break
                if ev is None:
                    self._f.flush()
                    return
            self._f.flush()

    def close(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=2)
        # the writer never leaves a trailing comma, so closing the array
        # yields strictly valid Chrome-trace JSON ("[]" when no events fired)
        self._f.write("\n]\n")
        self._f.close()


def _tid_allocator():
    tids = {}

    def tid_for(name):
        t = tids.get(name)
        if t is None:
            t = len(tids) + 1
            tids[name] = t
        return t

    return tids, tid_for


def spans_to_events(span_list, trace_id=0):
    """Expand spans into Chrome-trace events: pid = rank, tid per tensor.

    Collective spans become up to three complete ("X") events — NEGOTIATE,
    WIRE, DEQUEUE — sharing the span id; block spans one "X" each; marks
    become instant events. Slots never filled (error paths) are skipped,
    so partial lifecycles still render.
    """
    events = []
    ranks = set()
    tid_maps = {}  # rank -> (dict, fn)

    def tid_for(rank, name):
        if rank not in tid_maps:
            tid_maps[rank] = _tid_allocator()
        return tid_maps[rank][1](name)

    hex_trace = "0x%x" % trace_id

    for sp in span_list:
        ranks.add(sp.rank)
        if sp.kind == S.K_COLLECTIVE:
            tid = tid_for(sp.rank, sp.name)
            args = {"tensor": sp.name, "op": sp.op, "nbytes": sp.nbytes,
                    "fused": sp.fused, "span_id": "0x%x" % sp.span_id,
                    "trace_id": hex_trace}
            phases = ((EV_NEGOTIATE, S.T_ENQ, S.T_NEG),
                      (EV_WIRE, S.T_WIRE_START, S.T_WIRE_END),
                      (EV_DEQUEUE, S.T_WIRE_END, S.T_DONE))
            for pname, b, e in phases:
                t0, t1 = sp.ts[b], sp.ts[e]
                if t0 <= 0 or t1 < t0:
                    continue
                events.append({"name": pname, "ph": "X", "pid": sp.rank,
                               "tid": tid, "ts": t0, "dur": t1 - t0,
                               "args": args})
        elif sp.kind in (S.K_STEP, S.K_PHASE, S.K_WAIT):
            t0, t1 = sp.ts[0], sp.ts[1]
            if t0 <= 0 or t1 < t0:
                continue
            events.append({"name": sp.name, "ph": "X", "pid": sp.rank,
                           "tid": 0, "ts": t0, "dur": t1 - t0,
                           "args": {"span_id": "0x%x" % sp.span_id}})
        elif sp.kind == S.K_MARK:
            events.append({"name": sp.name, "ph": "i", "pid": sp.rank,
                           "tid": 0, "ts": sp.ts[0], "s": "g"})

    # Metadata: name every rank's process and tensor thread so Perfetto
    # labels the rows.
    meta = []
    for rank in sorted(ranks):
        meta.append({"name": "process_name", "ph": "M", "pid": rank,
                     "args": {"name": "rank %d" % rank}})
        meta.append({"name": "thread_name", "ph": "M", "pid": rank, "tid": 0,
                     "args": {"name": "step"}})
        if rank in tid_maps:
            for tname, tid in tid_maps[rank][0].items():
                meta.append({"name": "thread_name", "ph": "M", "pid": rank,
                             "tid": tid, "args": {"name": tname}})
    return meta + events


def write_merged(path, span_list, trace_id=0, world_size=None):
    """Write one merged Chrome-trace JSON object for all ranks' spans."""
    doc = {
        "traceEvents": spans_to_events(span_list, trace_id=trace_id),
        "displayTimeUnit": "ms",
        "metadata": {
            "trace_id": "0x%x" % trace_id,
            "producer": "horovod_tpu.tracing",
        },
    }
    if world_size is not None:
        doc["metadata"]["world_size"] = world_size
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
