# Copyright 2018 Uber Technologies, Inc. All Rights Reserved.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or
# implied. See the License for the specific language governing
# permissions and limitations under the License.
# ==============================================================================
"""``hvdprof`` — critical-path profiler CLI over merged hvd traces.

Usage::

    hvdprof report  trace.json [--top N] [--json]
    hvdprof validate trace.json
"""

import argparse
import json
import sys

from . import analyzer


def _build_parser():
    p = argparse.ArgumentParser(
        prog="hvdprof",
        description="Analyze a merged horovod_tpu trace (HOROVOD_TRACE "
                    "output): per-step breakdown, exposed-communication %, "
                    "per-rank skew, slowest tensors.")
    sub = p.add_subparsers(dest="cmd")
    rep = sub.add_parser("report", help="print the critical-path report")
    rep.add_argument("trace", help="merged trace JSON file")
    rep.add_argument("--top", type=int, default=10,
                     help="how many slowest tensors to list")
    rep.add_argument("--json", action="store_true",
                     help="emit the raw report dict as JSON")
    val = sub.add_parser("validate",
                         help="check the file parses as Chrome-trace JSON")
    val.add_argument("trace")
    return p


def main(argv=None):
    args = _build_parser().parse_args(argv)
    if args.cmd is None:
        _build_parser().print_help()
        return 2
    if args.cmd == "validate":
        try:
            events = analyzer.load_events(args.trace)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print("invalid trace %s: %s" % (args.trace, e), file=sys.stderr)
            return 1
        if not events:
            # an empty or truncated file can parse as JSON ({} / []) yet
            # carry nothing — that is a failed trace run, not a valid one
            print("invalid trace %s: no trace events (empty or truncated "
                  "capture)" % args.trace, file=sys.stderr)
            return 1
        print("ok: %s (%d events)" % (args.trace, len(events)))
        return 0
    try:
        report = analyzer.analyze(args.trace, top=args.top)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print("failed to analyze %s: %s" % (args.trace, e), file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(analyzer.format_report(report, path=args.trace))
    return 0


if __name__ == "__main__":
    sys.exit(main())
