# Copyright 2018 Uber Technologies, Inc. All Rights Reserved.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or
# implied. See the License for the specific language governing
# permissions and limitations under the License.
# ==============================================================================
"""Span records and the bounded per-process span recorder.

A span is one row of the collective lifecycle: for ``K_COLLECTIVE`` the
five timestamps are enqueue → negotiated → wire-start → wire-end → done;
block kinds (step / phase / wait) use only the first two slots. All
timestamps are :func:`horovod_tpu.tracing.clock.trace_us` microseconds.

Completed spans land in a ring buffer capped by ``HOROVOD_TRACE_BUFFER``;
overflow drops the oldest span and bumps ``hvd_trace_dropped_events_total``
rather than growing without bound.
"""

import os
import threading
from collections import deque

# Span kinds.
K_COLLECTIVE = 0
K_STEP = 1
K_PHASE = 2
K_WAIT = 3
K_MARK = 4

# Timestamp slots for K_COLLECTIVE spans.
T_ENQ = 0
T_NEG = 1
T_WIRE_START = 2
T_WIRE_END = 3
T_DONE = 4

NUM_TS = 5

DEFAULT_BUFFER = 65536

# Tracks every span-record allocation so the no-op fast path can be
# asserted: with tracing disabled this must not move.
_allocations = 0


def allocation_count() -> int:
    return _allocations


class Span:
    __slots__ = ("kind", "rank", "name", "op", "span_id", "nbytes", "fused",
                 "ts")

    def __init__(self, kind, rank, name, op="", span_id=0, nbytes=0, fused=0,
                 ts=None):
        self.kind = kind
        self.rank = rank
        self.name = name
        self.op = op
        self.span_id = span_id
        self.nbytes = nbytes
        self.fused = fused
        self.ts = ts if ts is not None else [0] * NUM_TS

    def __repr__(self):
        return ("Span(kind=%d, rank=%d, name=%r, op=%r, id=%d, ts=%r)"
                % (self.kind, self.rank, self.name, self.op, self.span_id,
                   self.ts))


def buffer_capacity() -> int:
    try:
        cap = int(os.environ.get("HOROVOD_TRACE_BUFFER", DEFAULT_BUFFER))
    except ValueError:
        cap = DEFAULT_BUFFER
    return max(1, cap)


class SpanRecorder:
    """Per-process recorder: open spans by (rank, name), ring of completed.

    Thread-safe; every controller/engine thread funnels through the one
    process-wide instance installed by :mod:`horovod_tpu.tracing`.
    """

    def __init__(self, capacity=None):
        self._cap = capacity if capacity is not None else buffer_capacity()
        self._open = {}          # (rank, name) -> Span, in-flight collectives
        self._done = deque()     # completed spans, ring-bounded by _cap
        self._lock = threading.Lock()
        self._next_id = 0
        self._dropped_cb = None  # lazily bound metrics counter

    # -- internals ---------------------------------------------------------

    def _alloc_id(self, rank):
        # Globally unique across ranks: rank in the high bits, a local
        # counter below. Rank 0's handshake distributes the trace id, so
        # span ids only need per-trace uniqueness.
        self._next_id += 1
        return ((rank + 1) << 40) | self._next_id

    def _push(self, span):
        if len(self._done) >= self._cap:
            self._done.popleft()
            self._count_drop()
        self._done.append(span)

    def _count_drop(self):
        if self._dropped_cb is None:
            from ..metrics import instruments
            self._dropped_cb = instruments.trace_dropped_events()
        self._dropped_cb.inc()

    # -- collective lifecycle ---------------------------------------------

    def begin_collective(self, rank, name, op, nbytes, t):
        global _allocations
        with self._lock:
            _allocations += 1
            span = Span(K_COLLECTIVE, rank, name, op=op,
                        span_id=self._alloc_id(rank), nbytes=nbytes)
            span.ts[T_ENQ] = t
            # A duplicate in-flight name means the previous span never
            # finished (error path); push what we have rather than leak.
            prev = self._open.pop((rank, name), None)
            if prev is not None:
                self._push(prev)
            self._open[(rank, name)] = span

    def mark(self, rank, name, slot, t):
        with self._lock:
            span = self._open.get((rank, name))
            if span is not None and span.ts[slot] == 0:
                span.ts[slot] = t

    def set_fused(self, rank, name, fused):
        with self._lock:
            span = self._open.get((rank, name))
            if span is not None:
                span.fused = fused

    def finish(self, rank, name, t):
        with self._lock:
            span = self._open.pop((rank, name), None)
            if span is not None:
                span.ts[T_DONE] = t
                self._push(span)

    def abort(self, rank, name):
        with self._lock:
            self._open.pop((rank, name), None)

    # -- block spans (step / phase / wait) --------------------------------

    def begin_block(self, kind, rank, name, t):
        global _allocations
        with self._lock:
            _allocations += 1
            span = Span(kind, rank, name, span_id=self._alloc_id(rank))
            span.ts[0] = t
            return span

    def end_block(self, span, t):
        span.ts[1] = t
        with self._lock:
            self._push(span)

    def add_wait(self, rank, t0, t1):
        global _allocations
        with self._lock:
            _allocations += 1
            span = Span(K_WAIT, rank, "WAIT", span_id=self._alloc_id(rank))
            span.ts[0] = t0
            span.ts[1] = t1
            self._push(span)

    def add_mark(self, rank, name, t):
        global _allocations
        with self._lock:
            _allocations += 1
            span = Span(K_MARK, rank, name, span_id=self._alloc_id(rank))
            span.ts[0] = t
            self._push(span)

    # -- draining ---------------------------------------------------------

    def drain(self):
        """Pop all completed spans (in-flight ones stay open)."""
        with self._lock:
            out = list(self._done)
            self._done.clear()
        return out

    def pending(self) -> int:
        with self._lock:
            return len(self._done)

    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def open_spans(self):
        """Snapshot of in-flight collectives as (rank, name, ts) rows —
        the blackbox dump's open-span table: what each rank was still
        waiting on when the process died."""
        with self._lock:
            return [(s.rank, s.name, list(s.ts))
                    for s in self._open.values()]
