# Copyright 2018 Uber Technologies, Inc. All Rights Reserved.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or
# implied. See the License for the specific language governing
# permissions and limitations under the License.
# ==============================================================================
"""Cross-rank distributed tracing.

Per-tensor collective lifecycle spans (enqueue → negotiate → wire →
dequeue-done) recorded on every rank in a rank-0-aligned monotonic
timebase, shipped to the coordinator over ``MSG_TRACE`` frames, and
merged by rank 0 into one strictly-valid Chrome/Perfetto trace at the
path named by ``HOROVOD_TRACE``. ``bin/hvdprof`` analyzes the merged
file; see :mod:`horovod_tpu.tracing.analyzer`.

The whole subsystem is a no-op unless ``HOROVOD_TRACE`` is set:
``active()`` returns ``None`` and the engine's hot path does a single
attribute read per instrumentation site, allocating nothing.
"""

import os
import threading
from collections import deque

from . import clock  # noqa: F401  (re-exported for callers)
from .spans import (  # noqa: F401
    K_COLLECTIVE, K_MARK, K_PHASE, K_STEP, K_WAIT,
    NUM_TS, T_DONE, T_ENQ, T_NEG, T_WIRE_END, T_WIRE_START,
    Span, SpanRecorder, allocation_count, buffer_capacity,
)

_lock = threading.Lock()
_tracer = None        # SpanRecorder when HOROVOD_TRACE is set
_path = None          # merged-trace output path
_trace_id = 0         # rank 0 generates; workers learn it via MSG_CLOCK
_store = deque()      # rank 0 / local: completed spans from every rank
_store_cap = 0


def _resolve_path():
    raw = os.environ.get("HOROVOD_TRACE", "").strip()
    if not raw:
        return None
    if raw in ("1", "true", "True"):
        return "hvd_trace.json"
    return raw


def active():
    """The process tracer, or None when tracing is off (the fast path)."""
    return _tracer


def enabled() -> bool:
    return _tracer is not None


def maybe_activate():
    """Install the tracer iff ``HOROVOD_TRACE`` is set. Idempotent."""
    global _tracer, _path, _store_cap
    path = _resolve_path()
    if path is None:
        return None
    with _lock:
        if _tracer is None:
            _path = path
            _tracer = SpanRecorder()
            # Rank 0 aggregates every rank's spans; give the merged store
            # more headroom than one rank's ring.
            _store_cap = buffer_capacity() * 8
        return _tracer


def trace_path():
    return _path


def ensure_trace_id() -> int:
    """Rank 0: lazily mint the globally-unique trace id."""
    global _trace_id
    with _lock:
        if _trace_id == 0:
            _trace_id = (int.from_bytes(os.urandom(6), "big") << 16) \
                | (os.getpid() & 0xFFFF)
        return _trace_id


def set_trace_id(tid: int) -> None:
    """Workers: install the trace id learned from rank 0's handshake."""
    global _trace_id
    with _lock:
        _trace_id = int(tid)


def trace_id() -> int:
    return _trace_id


def store_batch(span_list) -> None:
    """Accept a batch of completed spans (local drain or MSG_TRACE)."""
    global _store
    if not span_list:
        return
    with _lock:
        overflow = len(_store) + len(span_list) - _store_cap
        if _store_cap and overflow > 0:
            from ..metrics import instruments
            for _ in range(min(overflow, len(_store))):
                _store.popleft()
            instruments.trace_dropped_events().inc(overflow)
            span_list = span_list[-_store_cap:]
        _store.extend(span_list)


def store_size() -> int:
    with _lock:
        return len(_store)


def flush_local() -> None:
    """Drain the tracer's ring straight into the local merged store.

    Used by rank 0 and by uncoordinated controllers, where there is no
    wire to ship spans over — same clock, same process, so spans go
    directly where MSG_TRACE batches would land.
    """
    tr = _tracer
    if tr is not None:
        store_batch(tr.drain())


def drain_store():
    with _lock:
        out = list(_store)
        _store.clear()
    return out


def finalize(mode="standalone", rank=0, world_size=None):
    """Write the merged trace (if this process owns one) and reset.

    Rank 0 — and any single-process mode — writes ``HOROVOD_TRACE``
    itself; a multiprocess worker that somehow still holds local spans
    (uncoordinated fallback) writes ``<path>.rank<N>`` instead of
    clobbering the merged file. Returns the written path or None.
    """
    global _tracer, _path, _trace_id, _store_cap
    tr = _tracer
    if tr is None:
        return None
    flush_local()
    spans = drain_store()
    path = _path
    out = None
    if spans and path:
        from .writer import write_merged
        if rank != 0 and mode == "multiprocess":
            path = "%s.rank%d" % (path, rank)
        out = write_merged(path, spans, trace_id=_trace_id,
                           world_size=world_size)
    with _lock:
        _tracer = None
        _path = None
        _trace_id = 0
        _store_cap = 0
    clock.reset()
    return out


def reset_for_tests() -> None:
    """Hard reset of all module state (unit tests only)."""
    global _tracer, _path, _trace_id, _store_cap
    with _lock:
        _tracer = None
        _path = None
        _trace_id = 0
        _store_cap = 0
        _store.clear()
    clock.reset()
