# Copyright 2018 Uber Technologies, Inc. All Rights Reserved.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or
# implied. See the License for the specific language governing
# permissions and limitations under the License.
# ==============================================================================
"""Critical-path analysis over a merged cross-rank trace.

Consumes the Chrome-trace JSON written by
:func:`horovod_tpu.tracing.writer.write_merged` (either the
``{"traceEvents": [...]}`` object or a bare event array) and produces the
numbers ``hvdprof`` reports: per-step breakdown (compute vs negotiation
vs wire vs straggler wait), exposed-communication %, per-rank skew, and
the top-k slowest tensors.
"""

import json
from collections import defaultdict

from .writer import EV_DEQUEUE, EV_NEGOTIATE, EV_STEP, EV_WAIT, EV_WIRE

_PHASE_NAMES = (EV_NEGOTIATE, EV_WIRE, EV_DEQUEUE)


def load_events(path):
    """Load trace events from a merged-object or bare-array trace file."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        events = doc.get("traceEvents", [])
    elif isinstance(doc, list):
        events = doc
    else:
        raise ValueError("unrecognized trace document in %s" % path)
    if not isinstance(events, list):
        raise ValueError("traceEvents is not a list in %s" % path)
    return events


def union_us(intervals):
    """Total covered microseconds of possibly-overlapping (ts, dur) spans.

    Negotiation windows of concurrently in-flight tensors overlap heavily;
    summing raw durations would overcount, so merge first.
    """
    ivs = sorted((ts, ts + max(0, dur)) for ts, dur in intervals)
    total = 0
    cur_a = cur_b = None
    for a, b in ivs:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        elif b > cur_b:
            cur_b = b
    if cur_b is not None:
        total += cur_b - cur_a
    return total


def _merge(intervals):
    """Sorted disjoint [a, b) list from possibly-overlapping (ts, dur)."""
    ivs = sorted((ts, ts + max(0, dur)) for ts, dur in intervals)
    out = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1][1] = b
        else:
            out.append([a, b])
    return out


def intersect_us(a_intervals, b_intervals):
    """Microseconds covered by BOTH span sets ((ts, dur) lists).

    Used for overlap accounting: wire time intersected with WAIT time is
    wire the step sat blocked on; the remainder of wire time ran while
    the rank was doing something else — communication hidden under
    compute (docs/overlap.md).
    """
    a_m, b_m = _merge(a_intervals), _merge(b_intervals)
    total = i = j = 0
    while i < len(a_m) and j < len(b_m):
        lo = max(a_m[i][0], b_m[j][0])
        hi = min(a_m[i][1], b_m[j][1])
        if hi > lo:
            total += hi - lo
        if a_m[i][1] <= b_m[j][1]:
            i += 1
        else:
            j += 1
    return total


def analyze(path, top=10):
    """Build the hvdprof report dict from a merged trace file."""
    events = load_events(path)
    xs = [e for e in events if e.get("ph") == "X"]

    # rank -> phase name -> [(ts, dur)]
    by_rank = defaultdict(lambda: defaultdict(list))
    # (tensor, per-rank occurrence idx) -> {rank: negotiate-start ts}
    neg_starts = defaultdict(dict)
    occ = defaultdict(int)  # (rank, tensor) -> occurrence counter
    # span_id -> accumulated lifecycle; tensor aggregation after
    span_dur = defaultdict(int)
    span_tensor = {}
    wire_spans = 0

    for e in xs:
        rank = e.get("pid", 0)
        name = e.get("name", "")
        ts = e.get("ts", 0)
        dur = e.get("dur", 0)
        by_rank[rank][name].append((ts, dur))
        args = e.get("args") or {}
        tensor = args.get("tensor")
        if name == EV_WIRE:
            wire_spans += 1
        if name in _PHASE_NAMES and tensor is not None:
            sid = args.get("span_id", "%s/%s" % (rank, tensor))
            span_dur[sid] += dur
            span_tensor[sid] = tensor
        if name == EV_NEGOTIATE and tensor is not None:
            key = (rank, tensor)
            neg_starts[(tensor, occ[key])][rank] = ts
            occ[key] += 1

    ranks = {}
    tot_step = tot_wait = tot_wire = tot_hidden = 0
    for rank in sorted(by_rank):
        groups = by_rank[rank]
        step_us = sum(d for _, d in groups.get(EV_STEP, []))
        neg_us = union_us(groups.get(EV_NEGOTIATE, []))
        wire_us = union_us(groups.get(EV_WIRE, []))
        deq_us = union_us(groups.get(EV_DEQUEUE, []))
        wait_us = union_us(groups.get(EV_WAIT, []))
        compute_us = max(0, step_us - wait_us)
        # wire time NOT under a WAIT span ran while this rank was busy
        # elsewhere (launching later buckets, backward compute) — hidden
        # communication; the bucket-overlap win this % makes visible
        hidden_us = wire_us - intersect_us(groups.get(EV_WIRE, []),
                                           groups.get(EV_WAIT, []))
        ranks[rank] = {
            "steps": len(groups.get(EV_STEP, [])),
            "step_us": step_us,
            "compute_us": compute_us,
            "negotiate_us": neg_us,
            "wire_us": wire_us,
            "dequeue_us": deq_us,
            "wait_us": wait_us,
            "exposed_comm_pct":
                (100.0 * wait_us / step_us) if step_us else 0.0,
            "overlap_pct":
                (100.0 * hidden_us / wire_us) if wire_us else 0.0,
        }
        tot_step += step_us
        tot_wait += wait_us
        tot_wire += wire_us
        tot_hidden += hidden_us

    # Straggler skew: for every (tensor, occurrence) group seen on >1 rank,
    # the spread of negotiation-start times is how long the fastest rank
    # sat waiting for the slowest.
    lags = defaultdict(list)  # rank -> [lag_us]
    max_skew = 0
    for starts in neg_starts.values():
        if len(starts) < 2:
            continue
        lo = min(starts.values())
        max_skew = max(max_skew, max(starts.values()) - lo)
        for rank, ts in starts.items():
            lags[rank].append(ts - lo)
    skew = {}
    for rank in sorted(lags):
        vals = lags[rank]
        skew[rank] = {"mean_us": sum(vals) / len(vals),
                      "max_us": max(vals), "samples": len(vals)}

    # Top-k slowest tensors by total lifecycle time.
    per_tensor = defaultdict(lambda: [0, 0])  # tensor -> [total_us, count]
    for sid, dur in span_dur.items():
        agg = per_tensor[span_tensor[sid]]
        agg[0] += dur
        agg[1] += 1
    slowest = sorted(
        ({"tensor": t, "total_us": v[0], "count": v[1],
          "mean_us": v[0] / v[1]}
         for t, v in per_tensor.items()),
        key=lambda r: -r["total_us"])[:top]

    return {
        "ranks": ranks,
        "overall": {
            "exposed_comm_pct":
                (100.0 * tot_wait / tot_step) if tot_step else 0.0,
            "overlap_pct":
                (100.0 * tot_hidden / tot_wire) if tot_wire else 0.0,
            "step_s": tot_step / 1e6,
            "wait_s": tot_wait / 1e6,
            "wire_s": tot_wire / 1e6,
            "hidden_wire_s": tot_hidden / 1e6,
            "max_skew_us": max_skew,
        },
        "skew": skew,
        "slowest": slowest,
        "counts": {
            "events": len(events),
            "x_events": len(xs),
            "wire_spans": wire_spans,
        },
    }


def _fmt_us(us):
    if us >= 1e6:
        return "%.3f s" % (us / 1e6)
    if us >= 1e3:
        return "%.3f ms" % (us / 1e3)
    return "%d us" % us


def format_report(report, path=""):
    """Render the analyze() dict as the hvdprof text report."""
    lines = []
    if path:
        lines.append("trace: %s" % path)
    c = report["counts"]
    lines.append("events: %d total, %d spans, %d wire spans"
                 % (c["events"], c["x_events"], c["wire_spans"]))
    lines.append("")
    lines.append("per-rank step breakdown")
    lines.append("  %-4s %5s %12s %12s %12s %12s %12s %8s %8s"
                 % ("rank", "steps", "step", "compute", "negotiate",
                    "wire", "wait", "exposed", "overlap"))
    for rank in sorted(report["ranks"]):
        r = report["ranks"][rank]
        lines.append("  %-4d %5d %12s %12s %12s %12s %12s %7.1f%% %7.1f%%"
                     % (rank, r["steps"], _fmt_us(r["step_us"]),
                        _fmt_us(r["compute_us"]), _fmt_us(r["negotiate_us"]),
                        _fmt_us(r["wire_us"]), _fmt_us(r["wait_us"]),
                        r["exposed_comm_pct"], r.get("overlap_pct", 0.0)))
    o = report["overall"]
    lines.append("")
    lines.append("exposed communication: %.1f%% of step time (%s wait / %s "
                 "step)" % (o["exposed_comm_pct"], _fmt_us(o["wait_s"] * 1e6),
                            _fmt_us(o["step_s"] * 1e6)))
    if "overlap_pct" in o:
        lines.append("overlap: %.1f%% of wire time hidden under compute "
                     "(%s hidden / %s wire)"
                     % (o["overlap_pct"], _fmt_us(o["hidden_wire_s"] * 1e6),
                        _fmt_us(o["wire_s"] * 1e6)))
    if report["skew"]:
        lines.append("")
        lines.append("per-rank straggler skew (lag behind fastest rank at "
                     "enqueue)")
        for rank in sorted(report["skew"]):
            s = report["skew"][rank]
            lines.append("  rank %-4d mean %10s  max %10s  (%d collectives)"
                         % (rank, _fmt_us(s["mean_us"]), _fmt_us(s["max_us"]),
                            s["samples"]))
        lines.append("  max cross-rank skew: %s" % _fmt_us(o["max_skew_us"]))
    if report["slowest"]:
        lines.append("")
        lines.append("slowest tensors (total lifecycle time)")
        for r in report["slowest"]:
            lines.append("  %-40s total %10s  mean %10s  x%d"
                         % (r["tensor"][:40], _fmt_us(r["total_us"]),
                            _fmt_us(r["mean_us"]), r["count"]))
    return "\n".join(lines)
