"""horovod_tpu — a TPU-native distributed data-parallel training framework.

A ground-up rebuild of the capabilities of Horovod v0.18.2 (reference:
Agoniii/horovod) for TPU: named asynchronous collectives (allreduce /
allgather / broadcast / adasum / join / alltoall) with tensor fusion, optimizer
and gradient wrappers averaging gradients across replicas, parameter broadcast,
fp16/bf16 compression, timeline profiling, stall detection, autotuning, and a
``horovodrun``-style launcher — implemented on XLA collectives over TPU
ICI/DCN meshes instead of NCCL/MPI/Gloo.

Typical use (JAX-native, eager parity API)::

    import horovod_tpu as hvd
    hvd.init()
    avg = hvd.allreduce(grad, name="g")          # psum/size over all ranks

SPMD fast path (the performance path — everything in one jitted step)::

    import horovod_tpu as hvd
    hvd.init()
    step = hvd.spmd.make_train_step(loss_fn, optimizer)
"""

from .utils import compat as _compat  # noqa: F401  (installs jax shims)

from .basics import (  # noqa: F401
    Adasum,
    Average,
    Sum,
    cross_rank,
    cross_size,
    ddl_built,
    gloo_built,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mesh,
    mlsl_built,
    mpi_built,
    gloo_enabled,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    num_replicas,
    rank,
    shutdown,
    size,
    xla_built,
)
from .exceptions import (  # noqa: F401
    CollectiveTimeoutError,
    DuplicateNameError,
    HorovodError,
    HorovodInternalError,
    NonFiniteError,
    NotInitializedError,
    ParameterDesyncError,
    RanksChangedError,
    ShutdownError,
    WorkerLostError,
)
from .ops.collective_ops import (  # noqa: F401
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    broadcast,
    broadcast_async,
    join,
    poll,
    synchronize,
)
from .ops.compression import Compression  # noqa: F401
from .ops.sparse import (  # noqa: F401
    IndexedSlices,
    allreduce_sparse,
)
from .optim.broadcast import (  # noqa: F401
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
)
from .optim.distributed import (  # noqa: F401
    DistributedAdasumOptimizer,
    DistributedGradientTape,
    DistributedOptimizer,
    allreduce_gradients,
    grad,
)
from . import callbacks  # noqa: F401
from .callbacks import ConsistencyCheckCallback, MetricsCallback  # noqa: F401
from . import checkpoint  # noqa: F401
from . import elastic  # noqa: F401
from . import integrity  # noqa: F401
from .integrity import ConsistencyAuditor, GradGuard  # noqa: F401
# NOTE: this import makes the *function* shadow the `horovod_tpu.metrics`
# module as a package attribute (hvd.metrics() returns the aggregated
# snapshot). The module stays importable as `from horovod_tpu.metrics
# import ...` / `import horovod_tpu.metrics` via sys.modules.
from .metrics import metrics  # noqa: F401
from . import parallel  # noqa: F401
from . import spmd  # noqa: F401
from . import tracing  # noqa: F401
from .run.api import run  # noqa: F401

__version__ = "0.1.0"
