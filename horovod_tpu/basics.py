"""Process model and global state — TPU-native equivalent of horovod's C ABI.

Reference parity: `horovod/common/basics.py` (HorovodBasics ctypes wrapper) and the
C API `horovod_init/shutdown/rank/size/local_rank/local_size/cross_rank/cross_size`
(`horovod/common/operations.cc:642-779`).

TPU-native design: there is no MPI. A *rank* is either
  - a JAX process in a multi-host job (``jax.distributed``-initialized; the launcher
    populates coordinator address / process id the way ``horovodrun`` populates
    ``HOROVOD_RANK``/``HOROVOD_GLOO_RENDEZVOUS_ADDR``; see `horovod/run/gloo_run.py:210-285`), or
  - a *thread-rank* bound to one local device, used by the in-process local cluster
    (the analogue of ``horovodrun -np N -H localhost:N`` for tests/benchmarks — the
    reference runs its whole test matrix this way, `.buildkite/gen-pipeline.sh:104-200`).

The MPI communicator triple GLOBAL/LOCAL/CROSS (`horovod/common/mpi/mpi_context.cc:150-158`)
maps onto device topology: LOCAL = ranks sharing a host (collectives ride ICI),
CROSS = one rank per host (collectives ride DCN).
"""

from __future__ import annotations

import contextvars
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

from .exceptions import NotInitializedError

logger = logging.getLogger("horovod_tpu")

# Reduce-op constants: parity with horovod/common/basics.py (Average/Sum/Adasum
# exported from horovod.torch / horovod.tensorflow).
Average = 0
Sum = 1
Adasum = 2

# Rank identity for the calling thread. In process mode this is unused (the
# process has exactly one rank); in local-cluster mode each worker thread carries
# its rank here.
_rank_ctx: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "hvd_tpu_rank", default=None
)


@dataclass
class _GlobalState:
    """Aggregate runtime state; mirrors HorovodGlobalState (`global_state.h:42-125`)."""

    initialized: bool = False
    mode: str = "standalone"  # standalone | cluster | multiprocess
    size: int = 1
    local_size: int = 1
    cross_size: int = 1
    rank0: int = 0  # this process's rank in multiprocess mode
    local_rank0: int = 0
    cross_rank0: int = 0
    # rank -> jax device that rank's tensors live on (cluster mode: 1:1;
    # process mode: this process's first addressable device).
    rank_devices: Sequence[Any] = field(default_factory=list)
    mesh: Any = None  # replica mesh: ALL devices, axis "hvd" (SPMD fast path)
    rank_mesh: Any = None  # one device per rank (eager engine collectives)
    engine: Any = None
    # elastic job (HVD_ELASTIC=1): jax.distributed is skipped so workers can
    # die/join; the engine routes collectives over the coordinator's host
    # wire instead of cross-process XLA (docs/elastic.md)
    elastic: bool = False
    lock: threading.Lock = field(default_factory=threading.Lock)


_state = _GlobalState()
_init_lock = threading.Lock()

MESH_AXIS = "hvd"


def _build_mesh(devices):
    from jax.sharding import Mesh

    return Mesh(np.asarray(devices), (MESH_AXIS,))


# reference logging.h level names (TRACE/FATAL have no stdlib equivalents;
# map to the nearest level the way glog-style loggers are usually bridged)
_LOG_LEVELS = {"TRACE": logging.DEBUG, "DEBUG": logging.DEBUG,
               "INFO": logging.INFO, "WARNING": logging.WARNING,
               "ERROR": logging.ERROR, "FATAL": logging.CRITICAL}


def _setup_logging() -> None:
    """Apply HOROVOD_LOG_LEVEL / HOROVOD_LOG_HIDE_TIME to the framework
    logger (reference `common/logging.{h,cc}`: leveled macro logger driven
    by the same envs, exported by the launcher's --log-level /
    --log-hide-timestamp flags). Only touches the ``horovod_tpu`` logger —
    never the root — and only adds a handler if the app hasn't."""
    level = os.environ.get("HOROVOD_LOG_LEVEL", "").upper()
    if level in _LOG_LEVELS:
        logger.setLevel(_LOG_LEVELS[level])
    if logger.handlers or logging.getLogger().handlers:
        return  # the application configured logging; respect it
    from .utils.env import env_on

    handler = logging.StreamHandler()
    fmt = "[%(asctime)s] %(levelname)s %(name)s: %(message)s"
    if env_on("HOROVOD_LOG_HIDE_TIME"):
        fmt = "%(levelname)s %(name)s: %(message)s"
    handler.setFormatter(logging.Formatter(fmt))
    logger.addHandler(handler)


def init(
    ranks: Optional[Sequence[int]] = None,
    *,
    _cluster_size: Optional[int] = None,
    _devices: Optional[Sequence[Any]] = None,
) -> None:
    """Initialize the framework. Idempotent (InitializeHorovodOnce,
    `operations.cc:585-631`).

    Modes:
      * **multiprocess** — launcher (or the user) set ``HVD_COORDINATOR_ADDR`` /
        ``HVD_NUM_PROCS`` / ``HVD_PROCESS_ID`` or already called
        ``jax.distributed.initialize``; each process is one rank.
      * **cluster** — internal: ``local_cluster``/``run_cluster`` passes
        ``_cluster_size`` and each worker thread is a rank bound to one device.
      * **standalone** — single process, rank 0 of 1; the SPMD fast path still
        uses every local device through the mesh.

    ``ranks`` (subset init, `basics.py:33-65` in the reference) is accepted for
    API parity; subsetting is only meaningful in multiprocess mode.
    """
    import jax

    global _state
    with _init_lock:
        if _state.initialized:
            return
        _setup_logging()
        coord = os.environ.get("HVD_COORDINATOR_ADDR")
        if _cluster_size is not None:
            devices = list(_devices) if _devices is not None else list(jax.devices())
            if _cluster_size > len(devices):
                raise ValueError(
                    f"local cluster size {_cluster_size} exceeds device count "
                    f"{len(devices)}"
                )
            devices = devices[:_cluster_size]
            st = _GlobalState(
                initialized=True,
                mode="cluster",
                size=_cluster_size,
                local_size=_cluster_size,
                cross_size=1,
                rank_devices=devices,
                mesh=_build_mesh(devices),
                rank_mesh=_build_mesh(devices),
            )
        elif os.environ.get("HVD_ELASTIC", "") not in ("", "0"):
            # Elastic job: jax.distributed is deliberately NOT initialized —
            # XLA's cross-process runtime cannot survive a worker dying, and
            # the whole point here is that the job outlives its members.
            # Each process runs single-process JAX; collective payloads ride
            # the coordinator's TCP channel (elastic/executor.py).
            nproc = int(os.environ.get("HVD_NUM_PROCS", "1"))
            pid = int(os.environ.get("HVD_PROCESS_ID", "0"))
            local_rank = int(os.environ.get("HVD_LOCAL_RANK", 0))
            local_size = int(os.environ.get("HVD_LOCAL_SIZE", 1))
            cross_rank = int(os.environ.get("HVD_CROSS_RANK", pid))
            cross_size = int(os.environ.get("HVD_CROSS_SIZE", nproc))
            devices = list(jax.devices())
            # every rank "lives" on this process's first device; size the list
            # past nproc so late joiners (pid >= initial nproc) still resolve
            rank_devices = [devices[0]] * max(nproc, pid + 1)
            st = _GlobalState(
                initialized=True,
                mode="multiprocess",
                size=nproc,
                local_size=local_size,
                cross_size=cross_size,
                rank0=pid,
                local_rank0=local_rank,
                cross_rank0=cross_rank,
                rank_devices=rank_devices,
                mesh=_build_mesh(devices[:1]),
                rank_mesh=_build_mesh(devices[:1]),
                elastic=True,
            )
        elif coord or jax.process_count() > 1:
            if coord:
                # must run BEFORE any backend-initializing jax call
                # (jax.distributed requirement); idempotent via try
                try:
                    jax.distributed.initialize(
                        coordinator_address=coord,
                        num_processes=int(os.environ["HVD_NUM_PROCS"]),
                        process_id=int(os.environ["HVD_PROCESS_ID"]),
                    )
                except RuntimeError as e:
                    # tolerate only double-initialization; a genuine
                    # coordination failure (bad address, timeout) must NOT
                    # silently degrade to un-synchronized single-process
                    # training
                    if "already" not in str(e).lower():
                        raise
            nproc = jax.process_count()
            pid = jax.process_index()
            # local/cross decomposition: ranks sharing a host form LOCAL (ICI);
            # one per host forms CROSS (DCN). Host identity from device process
            # affinity; launcher also exports HVD_LOCAL_RANK/SIZE.
            local_rank = int(os.environ.get("HVD_LOCAL_RANK", 0))
            local_size = int(os.environ.get("HVD_LOCAL_SIZE", 1))
            cross_rank = int(os.environ.get("HVD_CROSS_RANK", pid))
            cross_size = int(os.environ.get("HVD_CROSS_SIZE", nproc))
            # rank r's "home" device = first device owned by process r
            per_proc = {}
            for d in jax.devices():
                per_proc.setdefault(d.process_index, d)
            rank_devices = [per_proc[i] for i in range(nproc)]
            st = _GlobalState(
                initialized=True,
                mode="multiprocess",
                size=nproc,
                local_size=local_size,
                cross_size=cross_size,
                rank0=pid,
                local_rank0=local_rank,
                cross_rank0=cross_rank,
                rank_devices=rank_devices,
                mesh=_build_mesh(jax.devices()),
                rank_mesh=_build_mesh(rank_devices),
            )
        else:
            devices = list(jax.devices())
            st = _GlobalState(
                initialized=True,
                mode="standalone",
                size=1,
                local_size=1,
                cross_size=1,
                rank_devices=[devices[0]],
                mesh=_build_mesh(devices),
                rank_mesh=_build_mesh(devices[:1]),
            )
        from .runtime.engine import Engine

        st.engine = Engine(st)
        st.engine.start()
        _state = st
        if st.rank0 == 0:
            # aggregating process: serve /metrics when HOROVOD_METRICS_PORT
            # is set (rank 0 in multiprocess mode; the one process otherwise)
            from .metrics import maybe_start_server

            maybe_start_server()
            # live anomaly watch over the aggregated hvd_* registry when
            # HOROVOD_ANOMALY_WATCH is set (docs/observability.md)
            from .blackbox import watch as _watch

            _watch.maybe_start_watch()


_shutdown_hooks = []


def register_shutdown_hook(fn) -> None:
    """Framework surfaces register per-module cleanup (e.g. the torch
    handle-side maps) to run whenever the engine is torn down. Dedup by
    qualified name: module reimports (tests pop sys.modules) must replace
    their old hook, not accumulate copies that pin stale module objects."""
    key = (getattr(fn, "__module__", None), getattr(fn, "__qualname__", None))
    for i, existing in enumerate(_shutdown_hooks):
        if (getattr(existing, "__module__", None),
                getattr(existing, "__qualname__", None)) == key:
            _shutdown_hooks[i] = fn
            return
    _shutdown_hooks.append(fn)


def shutdown() -> None:
    """Stop the background engine and reset state (`operations.cc:636-640`)."""
    global _state
    with _init_lock:
        if not _state.initialized:
            return
        mode, rank0, world = _state.mode, _state.rank0, _state.size
        if _state.engine is not None:
            _state.engine.shutdown()
        _state = _GlobalState()
        from .metrics import clear_reports, instruments, stop_server
        from .goodput import ledger as _goodput_ledger

        # final-flush the attribution ledger and mark the process down
        # before the endpoint disappears
        _goodput_ledger.detach()
        instruments.up().set(0.0)
        stop_server()
        clear_reports()
        # engine shutdown already pushed/drained the final span batches;
        # rank 0 (or the single process) now owns writing the merged trace
        from . import tracing

        out = tracing.finalize(mode=mode, rank=rank0, world_size=world)
        if out:
            logger.info("merged trace written to %s (hvdprof report %s)",
                        out, out)
        # the black box only speaks on abnormal exit: a clean shutdown
        # just stops the watch and resets the recorder state
        from . import blackbox
        from .blackbox import watch as _watch

        _watch.stop_watch()
        blackbox.finalize()
    for fn in _shutdown_hooks:
        try:
            fn()
        except Exception:
            logger.exception("shutdown hook %r failed", fn)


def is_initialized() -> bool:
    return _state.initialized


def _require_init() -> _GlobalState:
    if not _state.initialized:
        raise NotInitializedError(
            "horovod_tpu has not been initialized; call hvd.init() first."
        )
    return _state


def rank() -> int:
    """Global rank of the caller (`operations.cc:665-668`)."""
    st = _require_init()
    if st.mode == "cluster":
        r = _rank_ctx.get()
        return 0 if r is None else r
    return st.rank0


def size() -> int:
    """Number of ranks (`operations.cc:677-680`)."""
    return _require_init().size


def local_rank() -> int:
    """Rank within the host / ICI domain (`operations.cc:670-674`)."""
    st = _require_init()
    if st.mode == "cluster":
        return rank()
    return st.local_rank0


def local_size() -> int:
    return _require_init().local_size


def cross_rank() -> int:
    """Host index / DCN-domain rank (`operations.cc` cross accessors)."""
    st = _require_init()
    if st.mode == "cluster":
        return 0
    return st.cross_rank0


def cross_size() -> int:
    return _require_init().cross_size


def mesh():
    """The 1-D rank mesh (axis name ``"hvd"``) collectives execute over."""
    return _require_init().mesh


def num_replicas() -> int:
    """Total devices participating in the SPMD fast path (= mesh size).

    In standalone mode this exceeds ``size()``: one process drives all local
    chips and the jitted step data-parallelizes over them.
    """
    return int(np.prod(list(_require_init().mesh.shape.values())))


def rank_device(r: Optional[int] = None):
    st = _require_init()
    return st.rank_devices[rank() if r is None else r]


def _engine():
    st = _require_init()
    return st.engine


def set_thread_rank(r: Optional[int]) -> None:
    """Bind the calling thread to rank ``r`` (local-cluster worker threads)."""
    _rank_ctx.set(r)


def is_homogeneous() -> bool:
    """True if every node in the job has the same number of ranks
    (reference `common/basics.py:122-129`). The launcher computes this
    GLOBAL fact over the whole hostfile and exports it identically to
    every rank as ``HVD_UNIFORM_LOCAL_SIZE`` (0 when heterogeneous) — a
    rank-local ``size == local_size * cross_size`` test is NOT exact
    (e.g. node sizes 4,2,1,1 satisfy it on one rank). Jobs without the
    launcher env (standalone / thread-cluster) are single-node and
    homogeneous by construction."""
    _require_init()
    uniform = os.environ.get("HVD_UNIFORM_LOCAL_SIZE")
    if uniform:  # empty string == unset (a wrapper's `export VAR=`)
        try:
            return int(uniform) > 0
        except ValueError:
            raise ValueError(
                f"HVD_UNIFORM_LOCAL_SIZE={uniform!r} is not an integer; "
                "the launcher exports the uniform local size (0 when "
                "hosts hold unequal rank counts)")
    return True


# --- build-capability probes: parity with horovod/common/basics.py ------------
def mpi_threads_supported() -> bool:
    return False


def mpi_enabled() -> bool:
    """Runtime-mode probe (`basics.py:151-160`): MPI is never the control
    or data plane here — the coordinator service + XLA collectives are."""
    return False


def gloo_enabled() -> bool:
    """Runtime-mode probe (`basics.py:171-179`): reports whether the
    non-MPI (coordinated / jax.distributed) control plane is active, the
    role Gloo mode plays in the reference."""
    return is_initialized()


def mpi_built() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def mlsl_built() -> bool:
    return False


def xla_built() -> bool:
    """TPU-native data plane: XLA collectives over ICI/DCN."""
    return True
