"""Checkpoint save/restore with the reference's rank-0 + broadcast pattern.

SURVEY §5: the reference has no native checkpoint format — the supported
pattern is "rank 0 saves via the framework; on start, state is broadcast"
(`tensorflow/__init__.py:139-227`, `torch/__init__.py:437-585`, the
examples' restore-then-broadcast). This module is the JAX-native version:
flax msgpack serialization, atomic writes, rank-0-only saving, and
restore that reads on the root and broadcasts bytes so worker hosts
without the file (or with stale copies) still start consistent.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
from flax import serialization

from . import basics
from .optim.broadcast import broadcast_from_root


def save(path: str, state: Any, overwrite: bool = True) -> bool:
    """Write ``state`` (any pytree) at ``path``; only rank 0 writes (the
    reference convention — every rank holds identical state under data
    parallelism). Returns True on the writing rank, False elsewhere.

    The write is atomic (temp file + rename): a crash mid-save leaves the
    previous checkpoint intact.
    """
    # Overwrite guard: every rank must take the same raise/return path or
    # the survivors hang in the next collective. The file may exist only on
    # rank 0's host (only rank 0 writes), so the verdict is rank 0's,
    # broadcast to everyone as a plain boolean; every rank then raises the
    # SAME FileExistsError naming the path. (Raising inside the broadcast
    # would surface as a generic re-wrapped error on non-root ranks — the
    # caller's `except FileExistsError` must work on all of them.)
    if not overwrite:
        if basics.is_initialized() and basics.size() > 1:
            exists = bool(broadcast_from_root(
                lambda: os.path.exists(path), 0,
                name=f"ckpt.guard.{path}"))
        else:
            exists = os.path.exists(path)
        if exists:
            raise FileExistsError(f"checkpoint exists: {path}")
    if basics.is_initialized() and basics.rank() != 0:
        return False
    data = serialization.to_bytes(jax.device_get(state))
    from .ckpt import bundle

    # the atomic temp-file + rename convention lives in ckpt/bundle.py now
    # (one code path for every checkpoint byte in the tree); with
    # HOROVOD_CKPT_DIR set this is literally the async bundle subsystem's
    # writer path, so legacy save() and bundle shards share semantics
    bundle.atomic_write_bytes(path, data)
    return True


def _orbax():
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:
        raise ImportError(
            "save_sharded/restore_sharded require orbax-checkpoint "
            "(pip install orbax-checkpoint); the replicated save/restore "
            "path has no such dependency") from e
    return ocp


def save_sharded(path: str, state: Any) -> None:
    """Checkpoint a pytree that contains SHARDED global arrays (ZeRO-1
    optimizer state, tensor-parallel params) via orbax: every host writes
    only the shards it owns, so nothing is gathered through one host's
    memory — the TPU-native extension of the reference's rank-0 pattern,
    needed once state stops being replicated (`optim/zero.py`). ``path``
    becomes a directory; all processes must call this collectively."""
    ocp = _orbax()
    ckptr = ocp.StandardCheckpointer()
    try:
        ckptr.save(os.path.abspath(path), state, force=True)
        ckptr.wait_until_finished()
    finally:
        ckptr.close()


def restore_sharded(path: str, template: Any) -> Any:
    """Restore a :func:`save_sharded` checkpoint with the SHARDINGS of
    ``template``: a pytree of device-placed arrays (or
    ``jax.ShapeDtypeStruct`` with shardings) matching the saved structure —
    each host reads only its shards and the restored arrays come back
    placed exactly like the template, so no broadcast pass is needed
    (unlike the replicated :func:`restore_and_broadcast` path). Every array
    leaf must carry a sharding; restoring onto an unplaced template would
    silently fall back to whatever topology saved the checkpoint."""
    ocp = _orbax()
    import numpy as np

    def abstract(leaf):
        shape = np.shape(leaf)
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            raise ValueError(
                "restore_sharded: template leaf has no sharding "
                f"(shape {shape}); pass device-placed arrays (e.g. via "
                "optim.zero.shard_opt_state / spmd.replicate) so the "
                "restore targets THIS topology, not the saving one")
        dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    target = jax.tree_util.tree_map(abstract, template)
    ckptr = ocp.StandardCheckpointer()
    try:
        return ckptr.restore(os.path.abspath(path), target)
    finally:
        ckptr.close()


def restore(path: str, template: Any) -> Any:
    """Load a checkpoint into the structure of ``template`` (local read —
    use :func:`restore_and_broadcast` in multi-rank jobs)."""
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


def restore_and_broadcast(path: str, template: Any,
                          root_rank: int = 0,
                          name: Optional[str] = None) -> Any:
    """Rank ``root_rank`` reads ``path``; every rank receives the state.

    The restore-then-broadcast idiom of the reference examples
    (`examples/tensorflow2_synthetic_benchmark.py:88-95`): worker hosts
    need no filesystem access to the checkpoint, and ranks can never start
    from different files. Root-side read errors surface on every rank.
    """
    payload = broadcast_from_root(
        lambda: open(path, "rb").read(), root_rank,
        name=name or f"ckpt.{os.path.basename(path)}")
    return serialization.from_bytes(template, bytes(payload))
