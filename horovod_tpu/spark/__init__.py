"""Spark cluster integration — ``horovod_tpu.spark.run(fn, ...)``.

Reference parity: `horovod/spark/__init__.py:101-236` — `run(fn)` creates
``num_proc`` Spark tasks, collects host hashes through driver/task services,
then launches `mpirun` with a ``plm_rsh_agent`` that spawns orteds *inside*
Spark executors (`spark/driver/mpirun_rsh.py`, `spark/task/mpirun_exec_fn.py`)
and gathers per-rank results.

TPU-native redesign: there is no MPI control plane to smuggle into executors —
`jax.distributed` only needs every process to agree on a coordinator address
and a (rank, size) assignment. Spark *barrier mode* already gives both: all
``num_proc`` tasks run simultaneously, each knows its partition id (= rank)
and the full task-address list, and ``BarrierTaskContext.allGather`` is the
rendezvous. So the Spark tasks ARE the worker processes: each task sets the
same ``HVD_*`` env the `hvdrun` launcher would inject
(`run/launcher.py:61-78`), calls ``fn`` in-process, and results come back
through Spark's own collect — no ssh, no rsh agent, no result KV store.

Usage (driver program, e.g. a notebook)::

    import horovod_tpu.spark
    results = horovod_tpu.spark.run(train_fn, args=(lr,), num_proc=8)
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Callable, List, Optional

from .task import make_mapper


def _check_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark (pip install pyspark); "
            "it is not part of the base TPU image") from e


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None, extra_env: Optional[dict] = None,
        start_timeout: float = 600.0, verbose: bool = False) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` Spark tasks as a distributed
    job; returns per-rank results in rank order (`spark/__init__.py:101-236`).

    Raises ``RuntimeError`` if any rank fails (first traceback included) and
    ``TimeoutError`` if Spark has not *scheduled and started* all ``num_proc``
    barrier tasks within ``start_timeout`` seconds — the classic barrier-mode
    failure when the cluster is too small (the reference's settings.timeout
    likewise bounds startup only, `spark/__init__.py:142`). Once the tasks are
    running, the driver waits for completion with no time bound.
    """
    _check_pyspark()
    import time as _time

    from pyspark import SparkContext

    sc = SparkContext.getOrCreate()
    if num_proc is None:
        num_proc = sc.defaultParallelism
        if verbose:
            print(f"horovod_tpu.spark: num_proc defaulting to "
                  f"{num_proc} (spark default parallelism)")

    payload = _serialize((fn, tuple(args), dict(kwargs or {})))
    mapper = make_mapper(payload, num_proc, dict(extra_env or {}))

    rdd = sc.parallelize(range(num_proc), num_proc).barrier()

    out: dict = {}
    import uuid as _uuid

    job_group = f"horovod-tpu-{_uuid.uuid4().hex[:8]}"

    def _collect():
        try:
            # job groups are thread-local: tag in the submitting thread so
            # timeout/cancel touch only THIS job, never other work sharing
            # the SparkContext (e.g. a notebook's ETL jobs)
            try:
                sc.setJobGroup(job_group, "horovod_tpu.spark.run",
                               interruptOnCancel=True)
            except Exception:
                # untagged job = unobservable by the watchdog; mark it so the
                # driver waits instead of cancelling healthy work it can't see
                out["untagged"] = True
            out["results"] = rdd.mapPartitions(mapper).collect()
        except BaseException as e:  # surfaced after join
            out["error"] = e

    t = threading.Thread(target=_collect, daemon=True)
    t.start()
    deadline = (_time.time() + start_timeout
                if start_timeout and start_timeout > 0 else None)
    started = deadline is None
    tracker_dead_since = None
    while t.is_alive():
        if not started:
            running = _tasks_running(sc, num_proc, job_group)
            if running is None:
                tracker_dead_since = tracker_dead_since or _time.time()
            else:
                tracker_dead_since = None
            if (tracker_dead_since is not None
                    and _time.time() - tracker_dead_since >= 30.0):
                # Tracker continuously unobservable for 30s — API missing on
                # this Spark version/config, not a transient hiccup: better
                # to wait forever on a live job than kill one we cannot see,
                # but say so.
                import warnings

                warnings.warn(
                    "horovod_tpu.spark.run: Spark status tracker has been "
                    "unavailable for 30s; startup timeout is disabled for "
                    "this job")
                started = True
            elif "untagged" in out or running:
                started = True  # startup done (or unobservable); stop the clock
        if started:
            t.join(1.0)
        elif running is None:
            # tracker blind right now: never kill a job we cannot see, even
            # past the deadline (the 30s disarm above bounds this state)
            t.join(0.1)
        elif _time.time() >= deadline:
            try:
                sc.cancelJobGroup(job_group)
            except Exception:
                try:
                    sc.cancelAllJobs()
                except Exception:
                    pass
            raise TimeoutError(
                f"horovod_tpu.spark.run: not all {num_proc} tasks were "
                f"running after {start_timeout}s; is the cluster large "
                "enough for barrier mode to schedule all of them at once?")
        else:
            t.join(0.1)
    if "error" in out:
        raise out["error"]

    by_rank = sorted(out["results"], key=lambda r: r[0])
    failures = [(rank, err) for rank, ok, err in by_rank if not ok]
    if failures:
        rank, err = failures[0]
        raise RuntimeError(
            f"{len(failures)}/{num_proc} ranks failed; first failure "
            f"(rank {rank}):\n{err}")
    return [pickle.loads(blob) for _, _, blob in by_rank]


def _tasks_running(sc, num_proc: int, job_group: str):
    """True once Spark reports >= num_proc active tasks in OUR job group
    (barrier mode starts all-or-nothing; scoping to the group keeps
    concurrent unrelated jobs from masking a stuck barrier stage).
    Returns None when the tracker query itself fails, so the caller can
    tell "not started yet" apart from "tracker unobservable" — a transient
    query error must not silently disarm the startup timeout."""
    try:
        tracker = sc.statusTracker()
        total = 0
        for jid in tracker.getJobIdsForGroup(job_group):
            jinfo = tracker.getJobInfo(jid)
            if jinfo is None:
                continue
            for sid in jinfo.stageIds:
                sinfo = tracker.getStageInfo(sid)
                if sinfo is not None:
                    total += sinfo.numActiveTasks
        return total >= num_proc
    except Exception:
        return None


def _serialize(obj) -> bytes:
    try:
        import cloudpickle

        return cloudpickle.dumps(obj)
    except ImportError:
        return pickle.dumps(obj)
