"""Executor-side task body for :func:`horovod_tpu.spark.run`.

Reference parity: `horovod/spark/task/mpirun_exec_fn.py` +
`spark/__init__.py:36-68` (``_task_fn``) — but instead of exec-ing an orted
under mpirun, the barrier task IS the rank process: it derives its rank
assignment from the barrier context, performs rendezvous via ``allGather``,
injects the `hvdrun`-style env (`run/launcher.py:61-78`), and runs the user
function in-process.
"""

from __future__ import annotations

import os
import pickle
import socket
import traceback
from typing import Dict


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def rank_env_from_hosts(rank: int, hosts, coordinator_addr: str) -> Dict[str, str]:
    """Compute the LOCAL/CROSS communicator split (`mpi/mpi_context.cc:150-158`
    analogue) from the partition-ordered host list."""
    host = hosts[rank]
    local_rank = sum(1 for h in hosts[:rank] if h == host)
    local_size = sum(1 for h in hosts if h == host)
    host_order = list(dict.fromkeys(hosts))  # first-appearance order
    cross_rank = host_order.index(host)
    cross_size = len(host_order)
    return {
        "HVD_NUM_PROCS": str(len(hosts)),
        "HVD_PROCESS_ID": str(rank),
        "HVD_COORDINATOR_ADDR": coordinator_addr,
        "HVD_LOCAL_RANK": str(local_rank),
        "HVD_LOCAL_SIZE": str(local_size),
        "HVD_CROSS_RANK": str(cross_rank),
        "HVD_CROSS_SIZE": str(cross_size),
    }


def make_mapper(payload: bytes, num_proc: int, extra_env: Dict[str, str]):
    """Returns the mapPartitions body shipped to executors. The returned
    closure only captures picklable values (payload bytes, ints, dicts)."""

    def mapper(_iterator):
        from pyspark import BarrierTaskContext

        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        try:
            # rendezvous: everyone shares host + a locally-free port; rank 0's
            # pair becomes the jax.distributed coordinator address
            me = f"{socket.gethostname()}:{_free_port()}"
            members = ctx.allGather(me)
            hosts = [m.rsplit(":", 1)[0] for m in members]
            env = rank_env_from_hosts(rank, hosts, coordinator_addr=members[0])
            env.update(extra_env)
            os.environ.update(env)

            fn, args, kwargs = pickle.loads(payload)
            ok, blob = True, pickle.dumps(fn(*args, **kwargs))
        except Exception:
            ok, blob = False, traceback.format_exc()
        try:
            # failed ranks still join the final barrier so healthy ranks don't
            # die in it and mask the root cause; no rank exits before all
            # finished (uneven-exit teardown would kill stragglers'
            # collectives)
            ctx.barrier()
        except Exception:
            pass  # the stage is failing; the per-rank report survives
        yield (rank, ok, blob)

    return mapper
