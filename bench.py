#!/usr/bin/env python
"""Synthetic training benchmark — the reference's headline measurement.

Parity: `examples/tensorflow2_synthetic_benchmark.py` (synthetic
ImageNet-sized data, 10 warmup iters, 10 rounds x 10 timed iters, reports
img/sec ± 1.96σ) rebuilt on the SPMD fast path: the whole train step (forward,
backward, gradient averaging over the replica mesh, SGD update) is one XLA
program; batch sharded over replicas, params replicated.

``BENCH_MODEL`` selects the model family (default ResNet50; the reference's
scaling table also covers InceptionV3 and VGG16). Prints ONE JSON line:
  {"metric": "<model>_images_per_sec_per_chip", "value": N,
   "unit": "img/s/chip", "vs_baseline": N / 103.55}

``vs_baseline`` is non-null only for ResNet50, whose published denominator
exists: 1656.82 img/s on 16 Pascal GPUs = 103.55 img/s/GPU
(`docs/benchmarks.rst:43`, BASELINE.md).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="Synthetic training benchmark (env knobs: BENCH_MODEL, "
                    "BENCH_BATCH, BENCH_IMAGE, BENCH_WARMUP, BENCH_ROUNDS, "
                    "BENCH_ITERS).")
    p.add_argument("--metrics-dump", metavar="PATH", default=None,
                   help="write the final aggregated runtime-metrics snapshot "
                        "(hvd.metrics(), docs/metrics.md) as JSON to PATH")
    p.add_argument("--chaos", metavar="SPEC", default=None,
                   help="inject faults while benchmarking: a "
                        "HOROVOD_FAULT_SPEC string, e.g. "
                        "'conn_drop@tick:100;corrupt@frame:50' for the "
                        "control plane or 'nan@grad:50' / "
                        "'hang@collective:2:50' for the data-plane guards "
                        "(docs/fault-tolerance.md). Measures throughput "
                        "with recovery on the path")
    p.add_argument("--trace", metavar="PATH", default=None,
                   help="enable distributed tracing (sets HOROVOD_TRACE): "
                        "rank 0 writes one merged Chrome trace to PATH at "
                        "shutdown; analyze with bin/hvdprof report PATH "
                        "(docs/tracing.md). Adds a per-iteration device "
                        "sync so STEP spans bound real step time")
    p.add_argument("--history", metavar="PATH", default=None,
                   help="append this run's result to a schema-versioned "
                        "JSONL perf history (benchmarks/history.py)")
    p.add_argument("--check-regression", action="store_true",
                   help="with --history: compare this run against the "
                        "recorded trajectory BEFORE appending; exit 3 when "
                        "it falls below the tolerance floor")
    p.add_argument("--regression-window", type=int, default=None,
                   metavar="N", help="trailing records the baseline median "
                                     "uses (default 5)")
    p.add_argument("--regression-tolerance", type=float, default=None,
                   metavar="F", help="fraction below baseline that fails "
                                     "(default 0.15)")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.chaos:
        # must land before hvd.init(): the controller builds its injector
        # (and wraps its control socket) at connect time
        os.environ["HOROVOD_FAULT_SPEC"] = args.chaos
    if args.trace:
        # also before hvd.init(): the engine activates the tracer (and the
        # worker runs its clock handshake) during init
        os.environ["HOROVOD_TRACE"] = args.trace
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import models, spmd

    hvd.init()
    backend = jax.default_backend()
    n_dev = hvd.num_replicas()

    on_tpu = backend == "tpu"
    # BENCH_MODEL picks the reference benchmark family (the scaling table
    # covers ResNet, Inception V3 and VGG-16): ResNet50 | ResNet101 |
    # InceptionV3 | VGG16 | ...
    model_name = os.environ.get("BENCH_MODEL", "ResNet50")
    default_batch = {"InceptionV3": "128", "VGG16": "128", "VGG19": "128"}
    batch_per_device = int(os.environ.get(
        "BENCH_BATCH",
        default_batch.get(model_name, "256") if on_tpu else "4"))
    image_size = int(os.environ.get(
        "BENCH_IMAGE",
        ("299" if model_name == "InceptionV3" else "224") if on_tpu
        else ("139" if model_name == "InceptionV3" else "32")))
    warmup = int(os.environ.get("BENCH_WARMUP", "10" if on_tpu else "2"))
    num_rounds = int(os.environ.get("BENCH_ROUNDS", "10" if on_tpu else "2"))
    iters_per_round = int(os.environ.get("BENCH_ITERS", "10" if on_tpu else "2"))

    batch = batch_per_device * n_dev
    model = getattr(models, model_name)(
        num_classes=1000, dtype=jnp.bfloat16 if on_tpu else jnp.float32)

    rng = jax.random.PRNGKey(0)
    images_h = np.random.RandomState(0).randn(
        batch, image_size, image_size, 3).astype(np.float32)
    labels_h = np.random.RandomState(1).randint(0, 1000, (batch,))

    variables = model.init(rng, jnp.zeros((1, image_size, image_size, 3),
                                          jnp.float32), train=True)
    params = variables["params"]
    has_bn = "batch_stats" in variables
    batch_stats = variables.get("batch_stats", {})
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = tx.init(params)

    mesh = hvd.mesh()
    params = spmd.replicate(params, mesh)
    batch_stats = spmd.replicate(batch_stats, mesh)
    opt_state = spmd.replicate(opt_state, mesh)
    images = spmd.shard_batch(jnp.asarray(images_h), mesh)
    labels = spmd.shard_batch(jnp.asarray(labels_h), mesh)

    def loss_fn(p, bs, x, y):
        if has_bn:
            logits, new_state = model.apply(
                {"params": p, "batch_stats": bs}, x, train=True,
                mutable=["batch_stats"])
            new_bs = new_state["batch_stats"]
        else:  # e.g. VGG: no BN; dropout keyed per-compile is fine here
            logits = model.apply({"params": p}, x, train=True,
                                 rngs={"dropout": jax.random.PRNGKey(7)})
            new_bs = bs
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, new_bs

    from jax.sharding import NamedSharding, PartitionSpec as P
    repl = NamedSharding(mesh, P())

    def _train_step(p, bs, opt, x, y):
        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, bs, x, y)
        updates, opt = tx.update(grads, opt, p)
        p = optax.apply_updates(p, updates)
        return p, new_bs, opt, loss

    # Donation is deliberately off: profiled on v5e it makes XLA insert ~370
    # extra aliasing copies (~0.7 GB/step) and costs ~8% on this HBM-bound
    # step; there is ample spare HBM (temp ≈ 9 GB of 16 GB) without it.
    jitted = jax.jit(_train_step, out_shardings=(repl, repl, repl, repl))
    # The step is HBM-bandwidth-bound (~790 GB/s avg of 819 peak, profiled);
    # the latency-hiding scheduler reclaims a few % of scheduling slack.
    # Fall back to the plain jit if this libtpu doesn't know the flag.
    train_step = jitted
    if on_tpu:
        try:
            train_step = jitted.lower(
                params, batch_stats, opt_state, images, labels,
            ).compile(compiler_options={
                "xla_tpu_enable_latency_hiding_scheduler": "true"})
        except Exception:
            train_step = jitted

    # warmup (includes compile); sync via host transfer — on the axon relay
    # platform block_until_ready on mesh-sharded outputs can return early
    for _ in range(warmup):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels)
    float(loss)

    # Time all rounds under one final sync: on the axon relay every host
    # sync costs a network round-trip + dispatch-pipeline drain (~9 ms/step
    # amortised at 10 iters/round, measured), which is launch overhead, not
    # step time. Async dispatch makes unsynced round boundaries meaningless
    # (every dispatch returns instantly; the wait lands on the final sync),
    # so the error bar comes from a short second pass that syncs per round —
    # its spread includes sync jitter, making the bar conservative.
    tracer = hvd.tracing.active() if args.trace else None
    t0 = time.perf_counter()
    for _ in range(num_rounds):
        for _ in range(iters_per_round):
            if tracer is not None:
                sp = tracer.begin_block(hvd.tracing.K_STEP, hvd.rank(),
                                        "STEP", hvd.tracing.clock.trace_us())
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, images, labels)
            if tracer is not None:
                # per-iteration sync so the STEP span bounds real device
                # time, not async-dispatch time (skews throughput; the
                # --trace help text says so)
                float(loss)
                tracer.end_block(sp, hvd.tracing.clock.trace_us())
    float(loss)
    total = time.perf_counter() - t0
    mean = batch * iters_per_round * num_rounds / total

    round_rates = []
    for _ in range(min(num_rounds, 3)):
        r0 = time.perf_counter()
        for _ in range(iters_per_round):
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, images, labels)
        float(loss)
        round_rates.append(batch * iters_per_round /
                           (time.perf_counter() - r0))
    conf = float(1.96 * np.std(round_rates))
    per_chip = mean / n_dev
    print(f"# backend={backend} devices={n_dev} batch/device={batch_per_device} "
          f"img={image_size} loss={float(loss):.3f}", file=sys.stderr)
    print(f"# Img/sec total: {mean:.1f} +- {conf:.1f}; per chip: {per_chip:.1f}",
          file=sys.stderr)
    result = {
        "metric": f"{model_name.lower()}_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/s/chip",
        # the published per-GPU baseline exists only for the ResNet bench
        # (103.55 img/s, BASELINE.md) — a ratio for other models would
        # compare against the wrong denominator
        "vs_baseline": (round(per_chip / 103.55, 3)
                        if model_name == "ResNet50" else None),
        # denominator context so the ratio cannot mislead on its own: it
        # divides by the reference's 2017-era per-GPU number — from its
        # ResNet-101 illustrative run, the only published figure — not a
        # same-generation or same-model part; the roofline story lives in
        # docs/benchmarks.md (this step runs at ~97% of v5e HBM bandwidth)
        "baseline_denominator": (
            "103.55 img/s per Pascal GPU, 2017, from the reference's "
            "ResNet-101 run (docs/benchmarks.rst:43) — its only published "
            "throughput figure" if model_name == "ResNet50" else None),
    }
    print(json.dumps(result))

    rc = 0
    if args.history:
        from benchmarks.history import (append_record, check_regression,
                                        load_history)

        # compare against the trajectory BEFORE appending: today's run
        # must not be allowed to vote in its own baseline
        if args.check_regression:
            verdict = check_regression(
                load_history(args.history, metric=result["metric"]),
                result["value"],
                **{k: v for k, v in (
                    ("window", args.regression_window),
                    ("tolerance", args.regression_tolerance))
                   if v is not None})
            print("# regression check: %s" % json.dumps(verdict),
                  file=sys.stderr)
            if verdict["regression"]:
                print(f"# REGRESSION: {result['metric']} = "
                      f"{result['value']} fell below the floor "
                      f"{verdict['floor']} (baseline {verdict['baseline']} "
                      f"over {verdict['samples']} runs)", file=sys.stderr)
                rc = 3
        append_record(args.history, {
            "metric": result["metric"], "value": result["value"],
            "unit": result["unit"], "model": model_name,
            "backend": backend, "devices": n_dev,
            "batch_per_device": batch_per_device, "image_size": image_size,
        })
        print(f"# perf history appended to {args.history}", file=sys.stderr)

    if args.metrics_dump:
        with open(args.metrics_dump, "w") as f:
            json.dump(hvd.metrics(), f, indent=2, sort_keys=True)
        print(f"# metrics snapshot written to {args.metrics_dump}",
              file=sys.stderr)

    if args.trace:
        # the merged Chrome trace is written by rank 0 inside shutdown()
        hvd.shutdown()
        print(f"# trace written; analyze with: bin/hvdprof report "
              f"{args.trace}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
