#!/usr/bin/env python
"""ResNet-50 synthetic benchmark — the reference's headline measurement.

Parity: `examples/tensorflow2_synthetic_benchmark.py` (ResNet-50, synthetic
ImageNet-sized data, 10 warmup iters, 10 rounds x 10 timed iters, reports
img/sec ± 1.96σ) rebuilt on the SPMD fast path: the whole train step (forward,
backward, gradient averaging over the replica mesh, SGD update) is one XLA
program; batch sharded over replicas, params replicated.

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "img/s/chip", "vs_baseline": N / 103.55}

Baseline denominator: the reference's published illustrative throughput
1656.82 img/s on 16 Pascal GPUs = 103.55 img/s/GPU (`docs/benchmarks.rst:43`,
BASELINE.md).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import spmd
    from horovod_tpu.models.resnet import ResNet50

    hvd.init()
    backend = jax.default_backend()
    n_dev = hvd.num_replicas()

    on_tpu = backend == "tpu"
    batch_per_device = int(os.environ.get(
        "BENCH_BATCH", "128" if on_tpu else "4"))
    image_size = int(os.environ.get(
        "BENCH_IMAGE", "224" if on_tpu else "32"))
    warmup = int(os.environ.get("BENCH_WARMUP", "10" if on_tpu else "2"))
    num_rounds = int(os.environ.get("BENCH_ROUNDS", "10" if on_tpu else "2"))
    iters_per_round = int(os.environ.get("BENCH_ITERS", "10" if on_tpu else "2"))

    batch = batch_per_device * n_dev
    model = ResNet50(num_classes=1000,
                     dtype=jnp.bfloat16 if on_tpu else jnp.float32)

    rng = jax.random.PRNGKey(0)
    images_h = np.random.RandomState(0).randn(
        batch, image_size, image_size, 3).astype(np.float32)
    labels_h = np.random.RandomState(1).randint(0, 1000, (batch,))

    variables = model.init(rng, jnp.zeros((1, image_size, image_size, 3),
                                          jnp.float32), train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = tx.init(params)

    mesh = hvd.mesh()
    params = spmd.replicate(params, mesh)
    batch_stats = spmd.replicate(batch_stats, mesh)
    opt_state = spmd.replicate(opt_state, mesh)
    images = spmd.shard_batch(jnp.asarray(images_h), mesh)
    labels = spmd.shard_batch(jnp.asarray(labels_h), mesh)

    def loss_fn(p, bs, x, y):
        logits, new_state = model.apply(
            {"params": p, "batch_stats": bs}, x, train=True,
            mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, new_state["batch_stats"]

    from jax.sharding import NamedSharding, PartitionSpec as P
    repl = NamedSharding(mesh, P())

    @(lambda f: jax.jit(f, donate_argnums=(0, 1, 2),
                        out_shardings=(repl, repl, repl, repl)))
    def train_step(p, bs, opt, x, y):
        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, bs, x, y)
        updates, opt = tx.update(grads, opt, p)
        p = optax.apply_updates(p, updates)
        return p, new_bs, opt, loss

    # warmup (includes compile); sync via host transfer — on the axon relay
    # platform block_until_ready on mesh-sharded outputs can return early
    for _ in range(warmup):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels)
    float(loss)

    img_secs = []
    for _ in range(num_rounds):
        t0 = time.perf_counter()
        for _ in range(iters_per_round):
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, images, labels)
        float(loss)
        dt = time.perf_counter() - t0
        img_secs.append(batch * iters_per_round / dt)

    mean = float(np.mean(img_secs))
    conf = float(1.96 * np.std(img_secs))
    per_chip = mean / n_dev
    print(f"# backend={backend} devices={n_dev} batch/device={batch_per_device} "
          f"img={image_size} loss={float(loss):.3f}", file=sys.stderr)
    print(f"# Img/sec total: {mean:.1f} +- {conf:.1f}; per chip: {per_chip:.1f}",
          file=sys.stderr)
    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(per_chip / 103.55, 3),
    }))


if __name__ == "__main__":
    main()
