"""Build system for horovod_tpu.

Reference parity: the reference's `setup.py` (1460 LoC) builds one C++
extension per framework, gated by `HOROVOD_WITH[OUT]_*` env feature flags,
with compile-probing via `test_compile` (setup.py:352-620). Here there is one
native target — the engine core `libhvd_tpu_core.so` (controller, fusion
planner, response cache, timeline writer, GP autotuner) loaded via ctypes —
and the feature flags are:

  HOROVOD_TPU_WITH_NATIVE=1     require the native core (fail build if the
                                toolchain is missing) — mirrors HOROVOD_WITH_*
  HOROVOD_TPU_WITHOUT_NATIVE=1  skip the native build; the engine falls back
                                to the pure-Python controller — mirrors
                                HOROVOD_WITHOUT_*
  (default)                     best-effort: probe the compiler, build if
                                possible, otherwise warn and continue

The TPU compute path (XLA collectives, Pallas kernels) needs no compilation
here — jax/jaxlib ship it; there is deliberately no CUDA/NCCL probing
(HOROVOD_GPU_ALLREDUCE et al. have no TPU meaning).
"""

import os
import shutil
import subprocess
import sys
import tempfile
import textwrap

from setuptools import Command, find_packages, setup
from setuptools.command.build_py import build_py

_ROOT = os.path.dirname(os.path.abspath(__file__))
_CORE = os.path.join(_ROOT, "horovod_tpu", "_core")


def _env_flag(name):
    return os.environ.get(name, "").lower() in ("1", "true", "yes")


def _probe_compiler(cxx):
    """`test_compile` analogue (reference setup.py:352): can we build a
    trivial C++17 shared object with -pthread?"""
    if shutil.which(cxx) is None:
        return False
    with tempfile.TemporaryDirectory() as td:
        src = os.path.join(td, "probe.cc")
        with open(src, "w") as f:
            f.write(textwrap.dedent("""
                #include <atomic>
                #include <thread>
                extern "C" int hvd_probe() {
                    std::atomic<int> x{41};
                    return x.fetch_add(1) + 1;
                }
            """))
        out = os.path.join(td, "probe.so")
        r = subprocess.run(
            [cxx, "-std=c++17", "-fPIC", "-shared", "-pthread", src, "-o", out],
            capture_output=True)
        return r.returncode == 0


def _build_native(required):
    cxx = os.environ.get("CXX", "g++")
    if not _probe_compiler(cxx) or shutil.which("make") is None:
        msg = (f"toolchain probe failed (CXX={cxx!r}, make="
               f"{shutil.which('make')}); the native engine core will not be "
               "built (pure-Python controller fallback).")
        if required:
            raise RuntimeError(msg + " HOROVOD_TPU_WITH_NATIVE=1 was set.")
        print("WARNING:", msg, file=sys.stderr)
        return False
    r = subprocess.run(["make", "-C", _CORE, f"CXX={cxx}"],
                       capture_output=True, text=True)
    if r.returncode != 0:
        if required:
            raise RuntimeError("native core build failed:\n" + r.stderr)
        print("WARNING: native core build failed; continuing without it:\n"
              + r.stderr, file=sys.stderr)
        return False
    return True


class build_native(Command):
    """`python setup.py build_native` — build just libhvd_tpu_core.so."""

    user_options = []

    def initialize_options(self):
        pass

    def finalize_options(self):
        pass

    def run(self):
        _build_native(required=True)


class build_py_with_native(build_py):
    def run(self):
        if not _env_flag("HOROVOD_TPU_WITHOUT_NATIVE"):
            _build_native(required=_env_flag("HOROVOD_TPU_WITH_NATIVE"))
        super().run()


setup(
    name="horovod_tpu",
    version="0.1.0",
    description=("TPU-native distributed training framework with the "
                 "capabilities of Horovod: named async collectives, tensor "
                 "fusion, distributed optimizers, timeline, autotune, and a "
                 "horovodrun-style launcher — on XLA collectives over "
                 "ICI/DCN meshes."),
    packages=find_packages(include=["horovod_tpu", "horovod_tpu.*"]),
    package_data={
        "horovod_tpu": ["_core/*.cc", "_core/*.h", "_core/Makefile",
                        "_core/libhvd_tpu_core.so"],
    },
    python_requires=">=3.10",
    install_requires=["jax", "numpy"],
    extras_require={
        "flax": ["flax", "optax"],
        "torch": ["torch"],
        "test": ["pytest", "flax", "optax", "Pillow"],
    },
    entry_points={
        "console_scripts": [
            "hvdrun = horovod_tpu.run.launcher:main",
            "horovodrun = horovod_tpu.run.launcher:main",
        ],
    },
    cmdclass={"build_py": build_py_with_native,
              "build_native": build_native},
)
