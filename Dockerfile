# Test/CI image — the reference ships Dockerfile.cpu/.gpu plus a
# docker-compose version matrix; TPU runtimes are provisioned by the cloud
# host, so one CPU image covers build + the virtual-device test strategy.
#
#   docker build -t horovod-tpu-test .
#   docker run --rm horovod-tpu-test ci/run_tests.sh quick
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make openssh-client default-jre-headless && \
    rm -rf /var/lib/apt/lists/*

RUN pip install --no-cache-dir \
        "jax[cpu]" flax optax chex einops ml_dtypes numpy pytest \
        cloudpickle tensorflow-cpu pyspark orbax-checkpoint && \
    pip install --no-cache-dir torch \
        --index-url https://download.pytorch.org/whl/cpu

WORKDIR /workspace
COPY . .
RUN python setup.py build_native

CMD ["ci/run_tests.sh", "quick"]
